"""Resilience-layer tests (tier-1, CPU).

Policy tests run without JAX: the chaos injector's determinism, the
circuit-breaker state machine on a fake clock, the brownout controller's
engage/restore hysteresis on fake metrics, and the requeue path's
ordering/dedup/no-double-dispatch contract against a bare ``BucketQueue``.
Engine tests run the REAL tiny model through injected faults: a crashed
dispatch recovers via retry with the result still matching solo
inference, exhausted retries poison with the typed error, the no-chaos
dispatch path stays bitwise-equal to the solo runner, and a warm
restart restores executables from the persistent disk cache.  Checkpoint
tests pin the atomic-save contract (a truncated checkpoint can never be
resumed from; resume-from-latest-valid skips it).
"""

import io
import json
import os
import threading
import time
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from raft_stereo_tpu.serving.batcher import (BucketQueue, Overloaded,
                                             Request, RequestPoisoned)
from raft_stereo_tpu.serving.chaos import (ChaosConfig, ChaosInjector,
                                           InjectedResourceExhausted,
                                           InjectedWorkerCrash,
                                           parse_chaos_spec)
from raft_stereo_tpu.serving.resilience import (CIRCUIT_CLOSED,
                                                CIRCUIT_HALF_OPEN,
                                                CIRCUIT_OPEN,
                                                BrownoutController,
                                                CircuitBreaker, cost_ladder)

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")
ITERS = 1


# ----------------------------------------------------------- chaos injector
def test_chaos_off_by_default():
    from raft_stereo_tpu.serving.engine import ServeConfig

    assert ServeConfig().chaos is None
    assert not ChaosConfig().enabled
    assert ChaosConfig(crash_rate=0.1).enabled


def test_chaos_injector_is_deterministic_per_stream():
    """Two injectors with the same seed inject the identical fault
    sequence per (site, worker) stream, independent of the other
    worker's interleaving — the property chaos CI repros rest on."""
    def crash_pattern(inj, worker, n=200):
        out = []
        for _ in range(n):
            try:
                inj.on_dispatch(worker)
                out.append(False)
            except InjectedWorkerCrash:
                out.append(True)
        return out

    a = ChaosInjector(ChaosConfig(seed=3, crash_rate=0.1))
    b = ChaosInjector(ChaosConfig(seed=3, crash_rate=0.1))
    # interleave worker 1 draws on b only: worker 0's stream must not move
    for _ in range(50):
        try:
            b.on_dispatch(1)
        except InjectedWorkerCrash:
            pass
    pa, pb = crash_pattern(a, 0), crash_pattern(b, 0)
    assert pa == pb
    assert 5 <= sum(pa) <= 40      # ~10% of 200, loose deterministic band
    c = ChaosInjector(ChaosConfig(seed=4, crash_rate=0.1))
    assert crash_pattern(c, 0) != pa   # seed actually matters


def test_chaos_injector_respects_device_targeting_and_budget():
    inj = ChaosInjector(ChaosConfig(seed=0, crash_rate=1.0, devices=(1,),
                                    max_faults=2))
    inj.on_dispatch(0)              # untargeted worker: never faults
    with pytest.raises(InjectedWorkerCrash):
        inj.on_dispatch(1)
    with pytest.raises(InjectedWorkerCrash):
        inj.on_dispatch(1)
    inj.on_dispatch(1)              # budget exhausted: healthy again
    assert inj.faults_injected == 2


def test_chaos_resource_exhausted_message_matches_xla():
    inj = ChaosInjector(ChaosConfig(seed=0, resource_exhausted_rate=1.0))
    with pytest.raises(InjectedResourceExhausted, match="RESOURCE_EXHAUSTED"):
        inj.on_dispatch(0)


def test_parse_chaos_spec():
    assert parse_chaos_spec(None) is None
    assert parse_chaos_spec("") is None
    cc = parse_chaos_spec("crash=0.1,seed=7,latency_ms=50,latency=0.2,"
                          "devices=0|2,max_faults=5")
    assert cc == ChaosConfig(seed=7, crash_rate=0.1, latency_rate=0.2,
                             latency_ms=50.0, devices=(0, 2), max_faults=5)
    with pytest.raises(ValueError):
        parse_chaos_spec("bogus=1")
    with pytest.raises(ValueError):
        ChaosConfig(crash_rate=1.5)


# ---------------------------------------------------------- circuit breaker
def test_circuit_breaker_state_machine():
    clock = [0.0]
    transitions = []
    br = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                        clock=lambda: clock[0],
                        on_state=lambda o, n, f: transitions.append((o, n)))
    assert br.state == CIRCUIT_CLOSED and br.until_allowed() == 0.0
    assert not br.record_failure()          # 1 of 2: still closed
    br.record_success()                     # success resets the streak
    assert not br.record_failure()
    assert br.record_failure()              # 2 consecutive: OPEN
    assert br.state == CIRCUIT_OPEN
    assert br.until_allowed() > 0           # quarantined
    clock[0] = 1.1                          # cooldown over
    assert br.until_allowed() == 0.0        # the half-open probe token
    assert br.state == CIRCUIT_HALF_OPEN
    assert br.until_allowed() > 0           # only ONE probe at a time
    br.record_failure()                     # probe failed: straight back
    assert br.state == CIRCUIT_OPEN
    clock[0] = 2.2
    assert br.until_allowed() == 0.0
    br.record_success()                     # probe succeeded
    assert br.state == CIRCUIT_CLOSED and br.until_allowed() == 0.0
    assert (CIRCUIT_CLOSED, CIRCUIT_OPEN) in transitions
    assert (CIRCUIT_HALF_OPEN, CIRCUIT_CLOSED) in transitions


# ---------------------------------------------------------------- brownout
class _FakeCounter:
    def __init__(self):
        self.value = 0


class _FakeMetrics:
    def __init__(self):
        self.queue_depth = _FakeCounter()
        self.admitted = _FakeCounter()
        self.deadline_missed = _FakeCounter()


def test_cost_ladder_orders_cheapest_first():
    from raft_stereo_tpu.config import parse_tier

    tiers = [parse_tier(s) for s in
             ("quality", "interactive", "balanced")]
    assert cost_ladder(tiers) == ["interactive", "balanced", "quality"]
    inline = [parse_tier(s) for s in ("a:0.2", "b:0.5", "c:0")]
    assert cost_ladder(inline) == ["b", "a", "c"]


def test_brownout_engages_on_saturation_and_restores_with_hysteresis():
    clock = [0.0]
    m = _FakeMetrics()
    bc = BrownoutController(
        m, max_queue=10, ladder=["interactive", "balanced", "quality"],
        engage_fraction=0.8, engage_s=1.0, restore_fraction=0.2,
        restore_s=3.0, clock=lambda: clock[0])
    assert bc.level == 0
    assert bc.degrade("quality") == "quality"       # level 0: no-op
    m.queue_depth.value = 9                          # saturated
    bc.check()                                       # pressure starts
    clock[0] = 0.5
    assert bc.check() == 0                           # not sustained yet
    clock[0] = 1.2
    assert bc.check() == 1                           # sustained: engage
    assert bc.degrade("quality") == "balanced"
    assert bc.degrade("balanced") == "interactive"
    assert bc.degrade("interactive") == "interactive"  # floor
    assert bc.degrade(None) is None                  # off-ladder passes
    clock[0] = 2.5
    assert bc.check() == 2                           # still saturated: next rung
    assert bc.degrade("quality") == "interactive"
    # mid-band depth (between watermarks) holds the level forever
    m.queue_depth.value = 5
    for t in (3.0, 5.0, 9.0, 20.0):
        clock[0] = t
        assert bc.check() == 2
    # calm below the restore watermark, but restore needs restore_s
    m.queue_depth.value = 1
    clock[0] = 21.0
    bc.check()
    clock[0] = 22.0
    assert bc.check() == 2                           # only 1s calm
    clock[0] = 24.1
    assert bc.check() == 1                           # 3.1s calm: one rung back
    clock[0] = 27.3
    assert bc.check() == 0                           # fully restored
    assert bc.degrade("quality") == "quality"


def test_brownout_engages_on_deadline_miss_rate():
    clock = [0.0]
    m = _FakeMetrics()
    bc = BrownoutController(
        m, max_queue=100, ladder=["interactive", "quality"],
        engage_fraction=0.9, engage_s=0.5, restore_fraction=0.1,
        restore_s=2.0, miss_rate=0.5, min_events=4,
        clock=lambda: clock[0])
    m.admitted.value, m.deadline_missed.value = 10, 6   # 60% missed
    bc.check()
    m.admitted.value, m.deadline_missed.value = 20, 12
    clock[0] = 0.6
    assert bc.check() == 1


# ------------------------------------------------------------- requeue path
def _req(bucket=(64, 96), t=None, tier=None):
    return Request(bucket=bucket, payload=None, future=Future(),
                   t_enqueue=time.monotonic() if t is None else t,
                   tier=tier)


def test_requeue_rejoins_ahead_of_fresh_requests():
    """Satellite: a retried (older) request re-enters a bucket FIFO that
    already holds fresh requests AHEAD of them — a crash must not also
    cost queue position — and the next pops re-decompose cleanly."""
    q = BucketQueue(max_batch=4, batch_sizes=(1, 2, 4), max_queue=16)
    old = [_req(t=1.0), _req(t=2.0)]
    for r in old:
        q.submit(r)
    batch = q.pop(timeout=5)                 # dispatch picks both up
    assert batch == old and q.depth == 0
    fresh = [_req(t=3.0), _req(t=4.0), _req(t=5.0)]
    for r in fresh:
        q.submit(r)
    assert q.requeue(batch) == 2             # crashed dispatch bounces back
    assert q.depth == 5
    redo = q.pop(timeout=5)
    # 5 queued -> batch of 4, admission-ordered: the two retried requests
    # lead, then the fresh ones
    assert redo == [old[0], old[1], fresh[0], fresh[1]]
    assert q.pop(timeout=5) == [fresh[2]]


def test_requeue_dedups_and_skips_resolved_requests():
    """Satellite: no double-dispatch — a request already back in its
    bucket is not inserted twice, and a request whose future resolved
    (poisoned / deadline) while it waited for backoff never re-enters."""
    q = BucketQueue(max_batch=2, batch_sizes=(1, 2), max_queue=8)
    a, b = _req(t=1.0), _req(t=2.0)
    q.submit(a), q.submit(b)
    batch = q.pop(timeout=5)
    assert batch == [a, b]
    b.future.set_exception(RequestPoisoned("boom", attempts=2))
    assert q.requeue(batch) == 1             # only `a` re-enters
    assert q.requeue(batch) == 0             # double requeue: all dupes
    assert q.depth == 1
    assert q.pop(timeout=5) == [a]
    assert q.depth == 0


def test_requeue_interleaves_with_fresh_by_tier_group():
    """Retried requests only jump the queue within their own
    (bucket, tier) group — other groups' FIFO order is untouched."""
    q = BucketQueue(max_batch=2, batch_sizes=(1, 2), max_queue=8)
    t_a = _req(t=1.0, tier="interactive")
    q.submit(t_a)
    batch = q.pop(timeout=5)
    q.submit(_req(t=2.0, tier="quality"))
    q.submit(_req(t=3.0, tier="interactive"))
    assert q.requeue(batch) == 1
    # oldest-head group wins: the interactive group's head is t=1.0
    redo = q.pop(timeout=5)
    assert redo[0] is t_a and all(r.tier == "interactive" for r in redo)


def test_requeue_allowed_while_draining_but_not_closed():
    q = BucketQueue(max_batch=1, batch_sizes=(1,), max_queue=8)
    r = _req(t=1.0)
    q.submit(r)
    batch = q.pop(timeout=5)
    q.stop_admitting()
    with pytest.raises(Overloaded):
        q.submit(_req())                     # fresh work refused
    assert q.requeue(batch) == 1             # admitted work still retries
    assert q.pop(timeout=5) == [r]
    q.close()
    r2 = _req(t=2.0)
    assert q.requeue([r2]) == 0              # closed: typed failure instead
    with pytest.raises(Overloaded):
        r2.future.result(timeout=1)


# ----------------------------------------------------------- engine + chaos
@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


def _pairs(n, hw=(48, 64), seed=3):
    rng = np.random.default_rng(seed)
    lefts = [rng.integers(0, 255, hw + (3,), dtype=np.uint8)
             for _ in range(n)]
    rights = [np.roll(l, -3, axis=1) for l in lefts]
    return lefts, rights


def test_engine_recovers_crashed_dispatch_with_retry(tiny_model):
    """The headline recovery property: an injected crash mid-dispatch
    requeues the request, a fresh worker picks it up, and the answer is
    STILL bitwise-equal to solo inference — the client sees a slower
    response, never a broken one."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    want, _ = solo(lefts[0], rights[0])
    chaos = ChaosConfig(seed=1, crash_rate=1.0, max_faults=1)
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=ITERS, chaos=chaos,
            max_dispatch_attempts=3, retry_backoff_ms=1.0)) as svc:
        res = svc.infer(lefts[0], rights[0], timeout=300)
        assert res.attempts == 2
        assert np.array_equal(res.flow, want)
        assert svc.metrics.retries.value == 1
        assert svc.metrics.worker_restarts.value == 1
        assert svc.metrics.injected_faults("crash") == 1
        assert svc.metrics.completed.value == 1
        assert svc.metrics.poisoned.value == 0


def test_engine_poisons_after_exhausted_attempts(tiny_model):
    """A request that crashes on every bounded attempt fails individually
    with the typed RequestPoisoned — the server survives, the ledger
    balances, and a subsequent request (faults exhausted) succeeds."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    chaos = ChaosConfig(seed=1, crash_rate=1.0, max_faults=2)
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=ITERS, chaos=chaos,
            max_dispatch_attempts=2, retry_backoff_ms=1.0,
            breaker_failures=5, breaker_cooldown_s=0.05)) as svc:
        with pytest.raises(RequestPoisoned) as ei:
            svc.infer(lefts[0], rights[0], timeout=300)
        assert ei.value.attempts == 2
        assert isinstance(ei.value.last_error, InjectedWorkerCrash)
        assert svc.metrics.poisoned.value == 1
        assert svc.metrics.failed.value == 1
        # faults exhausted: the engine still serves
        res = svc.infer(lefts[0], rights[0], timeout=300)
        assert res.attempts == 1
        assert svc.metrics.completed.value == 1


def test_engine_circuit_breaker_quarantines_and_recovers(tiny_model):
    """The flapping-device story: consecutive failures open the device's
    circuit (gauge -> open), the cooldown's half-open probe succeeds once
    the flap ends, and the circuit closes — with every request answered."""
    from raft_stereo_tpu.serving import (CIRCUIT_CLOSED, ServeConfig,
                                         StereoService)

    cfg, variables = tiny_model
    lefts, rights = _pairs(2)
    fired = []

    class Sink:
        def fire(self, kind, **detail):
            fired.append(kind)

    chaos = ChaosConfig(seed=2, crash_rate=1.0, max_faults=2)
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=ITERS, chaos=chaos,
            max_dispatch_attempts=4, retry_backoff_ms=1.0,
            breaker_failures=2, breaker_cooldown_s=0.1)) as svc:
        svc.attach_anomaly_sink(Sink())
        svc.prewarm((48, 64))
        futs = [svc.submit(l, r) for l, r in zip(lefts, rights)]
        results = [f.result(timeout=300) for f in futs]
        assert all(np.isfinite(r.flow).all() for r in results)
        assert "circuit_open" in fired
        assert "circuit_closed" in fired
        assert fired.index("circuit_closed") > fired.index("circuit_open")
        assert "worker_crash" in fired
        assert svc.metrics.circuit_gauge(0).value == CIRCUIT_CLOSED


def test_engine_no_chaos_dispatch_bitwise_unchanged(tiny_model):
    """The zero-overhead contract: chaos unset (and even a ChaosConfig
    with all rates 0) leaves the dispatch path producing bitwise the
    solo runner's output, with no retries, restarts, or injections."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(2)
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    for chaos in (None, ChaosConfig()):   # unset and rate-0 both inert
        with StereoService(cfg, variables, ServeConfig(
                max_batch=1, batch_sizes=(1,), iters=ITERS,
                chaos=chaos)) as svc:
            assert svc.chaos is None      # rate-0 config never arms
            for l, r in zip(lefts, rights):
                res = svc.infer(l, r, timeout=300)
                want, _ = solo(l, r)
                assert np.array_equal(res.flow, want)
                assert res.attempts == 1
            m = svc.metrics
            assert (m.retries.value == m.worker_restarts.value
                    == m.poisoned.value == 0)


def test_engine_brownout_degrades_and_labels_results(tiny_model):
    """Brownout at level 1 reroutes an eligible quality request one rung
    down the ladder (result labeled with requested_tier/degraded), honors
    degradable=False and exempt tiers, and serves as-requested at
    level 0."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=4,
            tiers=("interactive:7.0:2", "balanced:3.0:2", "quality"),
            brownout=True, brownout_exempt_tiers=("interactive",),
            brownout_poll_s=3600.0)) as svc:   # poll inert: tests drive it
        assert svc.brownout is not None
        assert svc.brownout.ladder == ("interactive", "balanced",
                                       "quality")
        res = svc.infer(lefts[0], rights[0], tier="quality", timeout=300)
        assert res.tier == "quality" and not res.degraded
        with svc.brownout._lock:
            svc.brownout._set_level(1, "test")
        res = svc.infer(lefts[0], rights[0], tier="quality", timeout=300)
        assert res.tier == "balanced" and res.degraded
        assert res.requested_tier == "quality"
        res = svc.infer(lefts[0], rights[0], tier="quality",
                        degradable=False, timeout=300)
        assert res.tier == "quality" and not res.degraded
        res = svc.infer(lefts[0], rights[0], tier="interactive",
                        timeout=300)   # exempt tier: never degraded
        assert res.tier == "interactive" and not res.degraded
        assert svc.metrics.degraded.value == 1
        assert svc.metrics.brownout_level.value == 1


def test_serve_config_validates_resilience_knobs():
    from raft_stereo_tpu.serving.engine import ServeConfig

    with pytest.raises(ValueError, match="max_dispatch_attempts"):
        ServeConfig(max_dispatch_attempts=0)
    with pytest.raises(ValueError, match="breaker_failures"):
        ServeConfig(breaker_failures=0)
    with pytest.raises(ValueError, match="two configured tiers"):
        ServeConfig(brownout=True)
    with pytest.raises(ValueError, match="brownout_exempt_tiers"):
        ServeConfig(tiers=("interactive", "quality"),
                    brownout_exempt_tiers=("nope",))
    # valid combined config constructs
    ServeConfig(tiers=("interactive", "quality"), brownout=True,
                brownout_exempt_tiers=("quality",),
                chaos=ChaosConfig(crash_rate=0.5),
                max_dispatch_attempts=3)


# ----------------------------------------------------- persistent exe cache
def test_executable_disk_cache_roundtrip_and_corruption(tmp_path):
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.serving.persist import (ExecutableDiskCache,
                                                 executable_cache_key)

    cache = ExecutableDiskCache(str(tmp_path / "exe"))
    key = executable_cache_key(config="{}", bucket=(4, 4), batch=1,
                               tier=None, iters=1, fetch_dtype=None,
                               donate=False, device="0")
    assert cache.load(key) is None and cache.misses == 1
    compiled = jax.jit(lambda x: x * 2 + 1).lower(
        jnp.ones((4, 4))).compile()
    assert cache.store(key, compiled)
    exe = cache.load(key)
    assert exe is not None and cache.loads == 1
    np.testing.assert_array_equal(np.asarray(exe(jnp.ones((4, 4)))),
                                  np.full((4, 4), 3.0))
    # a truncated/corrupt entry degrades to a miss, never an error
    path = cache._path(key)
    with open(path, "wb") as f:
        f.write(b"torn")
    assert cache.load(key) is None
    # different coordinates -> different key (no false sharing)
    key2 = executable_cache_key(config="{}", bucket=(4, 4), batch=2,
                                tier=None, iters=1, fetch_dtype=None,
                                donate=False, device="0")
    assert key2 != key


@pytest.mark.slow
def test_engine_warm_restart_restores_from_disk(tiny_model, tmp_path):
    """Cold boot compiles + stores; a second engine over the same cache
    dir restores every executable (compiles_warm == cold's compiles_cold,
    zero cold compiles) and serves bitwise-identical results."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    sc = ServeConfig(max_batch=1, batch_sizes=(1,), iters=ITERS,
                     executable_cache_dir=str(tmp_path / "exe"),
                     warmup_shapes=((48, 64),))
    with StereoService(cfg, variables, sc) as svc:
        assert svc.ready
        n_cold = svc.metrics.compiles_cold.value
        assert n_cold >= 1 and svc.metrics.compiles_warm.value == 0
        res_cold = svc.infer(lefts[0], rights[0], timeout=300)
    with StereoService(cfg, variables, sc) as svc:
        assert svc.ready
        assert svc.metrics.compiles_warm.value == n_cold
        assert svc.metrics.compiles_cold.value == 0
        res_warm = svc.infer(lefts[0], rights[0], timeout=300)
        assert np.array_equal(res_warm.flow, res_cold.flow)


def test_engine_readiness_gates_on_declared_warm_surface(tiny_model):
    """prewarm_on_init=False: the engine declares its warm surface but is
    NOT ready until prewarm covers it; without warmup_shapes it is ready
    at boot (no declared surface)."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=ITERS,
            warmup_shapes=((48, 64),), prewarm_on_init=False)) as svc:
        assert not svc.ready
        st = svc.warm_status()
        assert st["warm_done"] == 0 and st["warm_target"] == 1
        svc.prewarm((48, 64))
        assert svc.ready
        assert svc.warm_status()["warm_done"] == 1
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=ITERS)) as svc:
        assert svc.ready                    # nothing declared = ready


# -------------------------------------------------------------- HTTP layer
def _post(url, body, content_type="application/x-npz", headers=()):
    req = urllib.request.Request(url, data=body, method="POST")
    req.add_header("Content-Type", content_type)
    for k, v in headers:
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _npz(left, right):
    buf = io.BytesIO()
    np.savez(buf, left=left, right=right)
    return buf.getvalue()


def test_http_overload_carries_retry_after_and_typed_body(tiny_model):
    """Satellite: 429 (queue full) and 503 (draining) both carry a
    Retry-After header and the machine-readable
    {"error": "overloaded", "retry_after_s": ...} body."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    body = _npz(lefts[0], rights[0])
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=1, batch_sizes=(1,),
                                    iters=ITERS, max_queue=1))
    server = StereoHTTPServer(svc, port=0).start()
    try:
        svc.queue.pause()                  # stage: fill the 1-deep queue
        t = threading.Thread(
            target=_post, args=(server.url + "/v1/disparity", body),
            daemon=True)
        t.start()
        deadline = time.monotonic() + 30
        while svc.queue.depth < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        status, headers, resp = _post(server.url + "/v1/disparity", body)
        assert status == 429
        assert "Retry-After" in headers
        payload = json.loads(resp)
        assert payload["error"] == "overloaded"
        assert payload["retry_after_s"] > 0
        assert payload["draining"] is False
        svc.queue.resume()
        t.join(timeout=300)
        svc.queue.stop_admitting()         # draining flavor
        status, headers, resp = _post(server.url + "/v1/disparity", body)
        assert status == 503
        assert "Retry-After" in headers
        payload = json.loads(resp)
        assert payload["error"] == "overloaded"
        assert payload["draining"] is True
        assert payload["retry_after_s"] >= 1
    finally:
        server.shutdown()
        svc.close()


def test_http_liveness_readiness_split(tiny_model):
    """/healthz (liveness) answers 200 while warming; /readyz is 503
    with warm progress until the declared ladder is warm, then 200."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=1, batch_sizes=(1,), iters=ITERS,
        warmup_shapes=((48, 64),), prewarm_on_init=False))
    server = StereoHTTPServer(svc, port=0).start()
    try:
        with urllib.request.urlopen(server.url + "/healthz",
                                    timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["status"] == "ok" and health["ready"] is False
        try:
            with urllib.request.urlopen(server.url + "/readyz",
                                        timeout=30) as resp:
                raise AssertionError(
                    f"/readyz must 503 while warming, got {resp.status}")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            ready = json.loads(e.read())
            assert ready["status"] == "warming"
            assert ready["warm_done"] == 0 and ready["warm_target"] == 1
        svc.prewarm((48, 64))
        with urllib.request.urlopen(server.url + "/readyz",
                                    timeout=30) as resp:
            ready = json.loads(resp.read())
        assert resp.status == 200 and ready["status"] == "ready"
    finally:
        server.shutdown()
        svc.close()


# --------------------------------------------------------- atomic checkpoint
def _tiny_cfg():
    from raft_stereo_tpu.config import RaftStereoConfig

    return RaftStereoConfig(**TINY)


def test_checkpoint_save_is_atomic_and_committed(tmp_path):
    from raft_stereo_tpu.training import checkpoint as ckpt

    cfg = _tiny_cfg()
    tree = {"params": {"w": np.arange(4.0)}, "step": np.asarray(7)}
    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(path, cfg, tree)
    assert ckpt.is_valid_checkpoint(path)
    with open(os.path.join(path, ckpt.COMMIT_FILE)) as f:
        commit = json.load(f)
    assert commit["complete"] is True and commit["step"] == 7
    # no staging/retired orphans left behind
    assert [e for e in os.listdir(tmp_path)] == ["ck"]
    _, restored = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(restored["params"]["w"],
                                  np.arange(4.0))
    # overwrite in place (the train loop's final checkpoint) stays atomic
    tree2 = {"params": {"w": np.arange(4.0) + 1}, "step": np.asarray(8)}
    ckpt.save_checkpoint(path, cfg, tree2)
    _, restored = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(restored["params"]["w"],
                                  np.arange(4.0) + 1)
    assert [e for e in os.listdir(tmp_path)] == ["ck"]


def test_truncated_checkpoint_is_invalid_and_skipped(tmp_path):
    """Regression: the torn-save shapes an unexpected kill used to
    produce — truncated config.json, missing/empty state — must fail
    validation, and resume-from-latest must fall back to the previous
    valid checkpoint instead of crash-looping."""
    from raft_stereo_tpu.training import checkpoint as ckpt

    cfg = _tiny_cfg()
    good = str(tmp_path / "100_run")
    ckpt.save_checkpoint(good, cfg,
                         {"params": {"w": np.zeros(2)},
                          "step": np.asarray(100)})
    torn = str(tmp_path / "200_run")
    ckpt.save_checkpoint(torn, cfg,
                         {"params": {"w": np.zeros(2)},
                          "step": np.asarray(200)})
    # tear it the old-fashioned way: truncate config.json mid-write
    with open(os.path.join(torn, ckpt.CONFIG_FILE), "w") as f:
        f.write('{"hidden_di')
    assert not ckpt.is_valid_checkpoint(torn)
    assert ckpt.latest_checkpoint(str(tmp_path), name="run") == good
    # a staging orphan (crash mid-save) is never a candidate
    os.makedirs(str(tmp_path / "300_run.tmp-123"))
    assert ckpt.latest_checkpoint(str(tmp_path), name="run") == good
    # empty state dir is torn too
    empty = str(tmp_path / "400_run")
    ckpt.save_checkpoint(empty, cfg, {"params": {"w": np.zeros(2)},
                                      "step": np.asarray(400)})
    state = os.path.join(empty, ckpt.STATE_DIR)
    import shutil
    shutil.rmtree(state)
    os.makedirs(state)
    assert not ckpt.is_valid_checkpoint(empty)
    assert ckpt.latest_checkpoint(str(tmp_path), name="run") == good


def test_latest_checkpoint_prefers_highest_step(tmp_path):
    from raft_stereo_tpu.training import checkpoint as ckpt

    cfg = _tiny_cfg()
    for step in (100, 300, 200):
        ckpt.save_checkpoint(str(tmp_path / f"{step}_run"), cfg,
                             {"params": {"w": np.zeros(2)},
                              "step": np.asarray(step)})
    assert ckpt.latest_checkpoint(str(tmp_path), name="run") == str(
        tmp_path / "300_run")
    # the final/preemption checkpoint (no step prefix) wins when its
    # COMMIT step is the highest — the actual preemption-resume case
    ckpt.save_checkpoint(str(tmp_path / "run"), cfg,
                         {"params": {"w": np.zeros(2)},
                          "step": np.asarray(350)})
    assert ckpt.latest_checkpoint(str(tmp_path), name="run") == str(
        tmp_path / "run")
    assert ckpt.latest_checkpoint(str(tmp_path), name="other") is None
