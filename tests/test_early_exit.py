"""Adaptive GRU early exit: the convergence-gated while-loop path.

The contracts pinned here (ISSUE round 12):

* parity pin — with ``exit_threshold_px <= 0`` the model runs today's
  fixed-depth scan program bitwise-unchanged (and keeps the 2-tuple
  return); with a threshold > 0 but ``min_iters == max_iters`` the
  while-loop path reproduces the scan output bitwise (the companion of
  test_costs' ``unroll_gru`` parity pin);
* loop semantics — the gate exits at the first iteration >= min_iters
  whose worst-batch-member mean |Δdisparity| drops below the threshold,
  and a batch pairing a converged-early image with a hard image rides to
  the hard image's solo depth (max-over-batch) with per-image results
  inside the engine's ladder tolerance;
* the serving tiers — per-tier executables, no cross-tier batching, the
  quality tier bitwise-equal to solo inference (the PR-6 contract), and
  the iters-used/saved telemetry.
"""

import dataclasses
import io
import json
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")
ITERS = 4
HW = (48, 64)


@pytest.fixture(scope="module")
def tiny_model():
    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


def _pair(seed=3, textured=True):
    if not textured:   # low-texture: no correlation signal, updates stall
        left = np.full(HW + (3,), 127, np.uint8)
        return left, left.copy()
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 255, HW + (3,), dtype=np.uint8)
    return left, np.roll(left, -3, axis=1)


def _as_batch(*imgs):
    return jnp.asarray(np.stack(imgs).astype(np.float32))


def _ee_model(cfg, **knobs):
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    return RAFTStereo(dataclasses.replace(cfg, **knobs))


def _delta_curve(model, variables, i1, i2, iters):
    """mean |Δdisparity| per iteration per image, reconstructed from
    fixed-depth scan runs — exactly the quantity the while-loop predicate
    reduces (disp_0 is the zero init)."""
    disps = [np.zeros_like(np.asarray(
        model.apply(variables, i1, i2, iters=1, test_mode=True)[0]))]
    for k in range(1, iters + 1):
        d, _ = model.apply(variables, i1, i2, iters=k, test_mode=True)
        disps.append(np.asarray(d))
    return [np.abs(disps[k] - disps[k - 1]).mean(axis=(1, 2))
            for k in range(1, iters + 1)]   # [k-1] -> per-image means


def _predicted_exit(deltas, threshold, min_iters, limit):
    """First iteration count the while-loop predicate admits an exit at:
    the loop checks the LAST transition's worst-member delta."""
    for k in range(min_iters, limit + 1):
        if max(deltas[k - 1]) < threshold:
            return k
    return limit


# ------------------------------------------------------------ config knobs
def test_config_validation():
    from raft_stereo_tpu.config import RaftStereoConfig

    with pytest.raises(ValueError, match="exit_min_iters"):
        RaftStereoConfig(exit_min_iters=0)
    with pytest.raises(ValueError, match="exit_max_iters"):
        RaftStereoConfig(exit_min_iters=4, exit_max_iters=2)
    with pytest.raises(ValueError, match="rows_gru"):
        RaftStereoConfig(exit_threshold_px=0.1, rows_shards=2,
                         rows_gru=True)


def test_parse_tier_presets_and_inline_specs():
    from raft_stereo_tpu.config import REQUEST_TIERS, parse_tier

    assert parse_tier("quality").exit_threshold_px <= 0
    assert parse_tier("interactive") is REQUEST_TIERS["interactive"]
    t = parse_tier("fast:0.5:3")
    assert (t.name, t.exit_threshold_px, t.min_iters) == ("fast", 0.5, 3)
    assert parse_tier("fast:0.5").min_iters == 1
    for bad in ("nope", "fast:abc", ":0.5", "a:1:2:3"):
        with pytest.raises(ValueError):
            parse_tier(bad)


def test_tier_apply_swaps_knobs_only(tiny_model):
    from raft_stereo_tpu.config import parse_tier

    cfg, _ = tiny_model
    t_cfg = parse_tier("interactive").apply(cfg)
    assert t_cfg.exit_threshold_px == 0.05 and t_cfg.exit_min_iters == 2
    assert dataclasses.replace(t_cfg, exit_threshold_px=0.0,
                               exit_min_iters=1) == cfg


# ------------------------------------------------------- model-level parity
def test_threshold_disabled_is_todays_scan_program(tiny_model):
    """exit_threshold_px <= 0 keeps the 2-tuple return and the exact scan
    output — the threshold-disabled parity pin."""
    cfg, variables = tiny_model
    base = _ee_model(cfg)
    off = _ee_model(cfg, exit_threshold_px=0.0, exit_min_iters=3)
    i1, i2 = map(_as_batch, _pair())
    out_base = base.apply(variables, i1, i2, iters=ITERS, test_mode=True)
    out_off = off.apply(variables, i1, i2, iters=ITERS, test_mode=True)
    assert len(out_base) == len(out_off) == 2
    np.testing.assert_array_equal(np.asarray(out_base[1]),
                                  np.asarray(out_off[1]))


def test_min_eq_max_reproduces_scan_bitwise(tiny_model):
    """Satellite pin (alongside test_costs' unroll_gru parity): the
    while-loop path at a pinned trip count is bitwise-equal to the
    fixed-iters scan."""
    cfg, variables = tiny_model
    base = _ee_model(cfg)
    ee = _ee_model(cfg, exit_threshold_px=0.01, exit_min_iters=ITERS,
                   exit_max_iters=ITERS)
    i1, i2 = map(_as_batch, _pair())
    d_scan, f_scan = base.apply(variables, i1, i2, iters=ITERS,
                                test_mode=True)
    d_ee, f_ee, used = ee.apply(variables, i1, i2, iters=ITERS,
                                test_mode=True)
    assert int(used) == ITERS
    np.testing.assert_array_equal(np.asarray(d_scan), np.asarray(d_ee))
    np.testing.assert_array_equal(np.asarray(f_scan), np.asarray(f_ee))


def test_exit_at_floor_matches_shallow_scan_bitwise(tiny_model):
    """A threshold above every update exits at the min_iters floor and the
    result equals the scan truncated there — intermediate disparities are
    valid outputs (the paper's framing), not a different computation."""
    cfg, variables = tiny_model
    ee = _ee_model(cfg, exit_threshold_px=1e9, exit_min_iters=2)
    i1, i2 = map(_as_batch, _pair())
    d_ee, f_ee, used = ee.apply(variables, i1, i2, iters=ITERS,
                                test_mode=True)
    assert int(used) == 2
    d2, f2 = _ee_model(cfg).apply(variables, i1, i2, iters=2,
                                  test_mode=True)
    np.testing.assert_array_equal(np.asarray(f2), np.asarray(f_ee))


def test_exit_max_iters_caps_below_caller_iters(tiny_model):
    cfg, variables = tiny_model
    ee = _ee_model(cfg, exit_threshold_px=1e-9, exit_min_iters=1,
                   exit_max_iters=3)
    i1, i2 = map(_as_batch, _pair())
    *_, used = ee.apply(variables, i1, i2, iters=ITERS, test_mode=True)
    assert int(used) <= 3


# --------------------------------------------------- convergence semantics
def test_batch_rides_to_worst_member_depth(tiny_model):
    """Satellite: the max-over-batch rule.  A threshold separating the
    easy (low-texture) and hard (textured) images' measured delta curves
    must (a) exit each solo run at its predicted iteration, (b) run the
    mixed batch to the HARD member's solo depth, and (c) keep each batch
    member's result within the engine's batch-N ladder tolerance of the
    fixed scan truncated at the batch's depth."""
    cfg, variables = tiny_model
    base = _ee_model(cfg)
    easy_l, easy_r = _pair(textured=False)
    hard_l, hard_r = _pair(seed=3)

    i1 = _as_batch(easy_l, hard_l)
    i2 = _as_batch(easy_r, hard_r)
    deltas = _delta_curve(base, variables, i1, i2, ITERS)  # per-image
    easy_c = [d[0] for d in deltas]
    hard_c = [d[1] for d in deltas]
    # a gate between the curves exists only if they separate after the
    # floor; the seeded tiny model separates by ~1 px (flat pairs have no
    # correlation signal to push updates)
    lo = max(easy_c[1:])          # easy must pass everywhere past floor
    hi = min(hard_c[1:ITERS])     # hard must fail until the cap
    assert lo < hi, (easy_c, hard_c)
    threshold = (lo + hi) / 2.0
    min_iters = 2

    ee = _ee_model(cfg, exit_threshold_px=float(threshold),
                   exit_min_iters=min_iters)
    k_easy = _predicted_exit([[d[0]] for d in deltas], threshold,
                             min_iters, ITERS)
    k_hard = _predicted_exit([[d[1]] for d in deltas], threshold,
                             min_iters, ITERS)
    assert k_easy < k_hard, (k_easy, k_hard)

    *_, used_easy = ee.apply(variables, _as_batch(easy_l),
                             _as_batch(easy_r), iters=ITERS,
                             test_mode=True)
    *_, used_hard = ee.apply(variables, _as_batch(hard_l),
                             _as_batch(hard_r), iters=ITERS,
                             test_mode=True)
    assert int(used_easy) == k_easy
    assert int(used_hard) == k_hard

    _, flows, used_batch = ee.apply(variables, i1, i2, iters=ITERS,
                                    test_mode=True)
    assert int(used_batch) == k_hard, \
        "the batch must ride to the worst member's solo depth"
    # Per-image parity at the batch's depth (the ladder tolerance the
    # engine documents for batch-N reassociation).
    flows = np.asarray(flows)
    for i, (l, r) in enumerate(((easy_l, easy_r), (hard_l, hard_r))):
        want = np.asarray(base.apply(variables, _as_batch(l), _as_batch(r),
                                     iters=k_hard, test_mode=True)[1])[0]
        # rtol covers the untrained fixture's large flow magnitudes —
        # reassociation drift scales with |flow| (the engine's 5e-4
        # ladder tolerance is stated for benchmark-regime disparities)
        np.testing.assert_allclose(flows[i], want, atol=5e-4, rtol=1e-4)


def test_runner_tracks_iters_used_and_batch_rule(tiny_model):
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    easy = _pair(textured=False)
    hard = _pair(seed=3)
    runner = InferenceRunner(cfg, variables, iters=ITERS,
                             exit_threshold_px=1e9, exit_min_iters=2)
    flow, _ = runner(*easy)
    assert runner.last_iters_used == 2
    runner(*hard)
    assert runner.iters_used_mean() == 2.0
    runner.reset_iters_used()
    assert runner.iters_used_mean() is None
    flows, _ = runner.run_batch([easy[0], hard[0]], [easy[1], hard[1]])
    assert flows.shape == (2,) + HW and runner.last_iters_used == 2

    fixed = InferenceRunner(cfg, variables, iters=ITERS)
    fixed(*easy)
    assert fixed.last_iters_used is None and fixed.iters_used_mean() is None


# ------------------------------------------------------------ serving tiers
def test_engine_tiers_parity_and_telemetry(tiny_model):
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair(seed=5)
    solo_full = InferenceRunner(cfg, variables, iters=ITERS)
    solo_floor = InferenceRunner(cfg, variables, iters=2)
    with StereoService(cfg, variables, ServeConfig(
            max_batch=2, iters=ITERS, cost_telemetry=True,
            tiers=("interactive:1e9:2", "quality"))) as svc:
        assert svc.default_tier == "quality"
        # quality == the fixed-depth program == bitwise solo parity (the
        # PR-6 contract survives tiers)
        r_q = svc.infer(left, right, tier="quality", timeout=120)
        assert r_q.tier == "quality" and r_q.iters_used == ITERS
        assert np.array_equal(r_q.flow, solo_full(left, right)[0])
        # default requests run the default tier
        assert svc.infer(left, right, timeout=120).tier == "quality"
        # interactive exits at its floor == the 2-iter fixed program
        r_i = svc.infer(left, right, tier="interactive", timeout=120)
        assert r_i.tier == "interactive" and r_i.iters_used == 2
        assert np.array_equal(r_i.flow, solo_floor(left, right)[0])
        # telemetry: per-tier trip-count histogram + saved counter
        hist, saved = svc.metrics.iters_used_stats("interactive")
        assert hist.count == 1 and saved.value == ITERS - 2
        q_hist, q_saved = svc.metrics.iters_used_stats("quality")
        assert q_hist.count == 2 and q_saved.value == 0
        text = svc.metrics.render_text()
        assert 'infer_gru_iters_used_count{tier="interactive"} 1' in text
        assert 'serve_gru_iters_saved_total{tier="interactive"} 2' in text
        # cost registry: the interactive family is a distinct executable,
        # quality shares the base (no tier suffix — one program)
        keys = {rec.key for rec in svc.costs.records()}
        assert "serving.forward(64x64,b1,tier=interactive)" in keys
        assert "serving.forward(64x64,b1)" in keys
        with pytest.raises(ValueError, match="unknown tier"):
            svc.infer(left, right, tier="nope", timeout=10)


def test_engine_never_batches_across_tiers(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair(seed=6)
    with StereoService(cfg, variables, ServeConfig(
            max_batch=8, iters=2, cost_telemetry=False,
            tiers=("interactive:1e9:1", "quality"))) as svc:
        svc.prewarm(HW)    # both executable families, all ladder rungs
        d0 = svc.metrics.batches.value
        svc.queue.pause()
        futs = [svc.submit(left, right, tier=t)
                for t in ("interactive", "quality",
                          "interactive", "quality")]
        svc.queue.resume()
        results = [f.result(timeout=120) for f in futs]
        # 4 requests, 2 per tier: tiers never share a dispatch, so the
        # scheduler issues exactly one batch-2 dispatch PER TIER
        assert svc.metrics.batches.value - d0 == 2
        assert [r.batch_size for r in results] == [2, 2, 2, 2]
        assert {r.tier for r in results} == {"interactive", "quality"}
        assert all(r.iters_used == (1 if r.tier == "interactive" else 2)
                   for r in results)


def test_engine_prewarm_covers_tier_families(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    with StereoService(cfg, variables, ServeConfig(
            max_batch=2, batch_sizes=(1, 2), iters=2, cost_telemetry=True,
            tiers=("interactive:1e9:1", "balanced:1e8:1",
                   "quality"))) as svc:
        svc.prewarm(HW)
        keys = {rec.key for rec in svc.costs.records()}
        for n in (1, 2):
            assert f"serving.forward(64x64,b{n})" in keys          # base
            assert f"serving.forward(64x64,b{n},tier=interactive)" in keys
            assert f"serving.forward(64x64,b{n},tier=balanced)" in keys
        # quality shares the base family — no quality-suffixed compiles
        assert not any("tier=quality" in k for k in keys)


def test_serve_config_tier_validation():
    from raft_stereo_tpu.serving import ServeConfig

    with pytest.raises(ValueError, match="duplicate"):
        ServeConfig(tiers=("interactive", "interactive:0.5:2"))
    with pytest.raises(ValueError, match="default_tier"):
        ServeConfig(tiers=("quality",), default_tier="interactive")
    with pytest.raises(ValueError, match="unknown tier"):
        ServeConfig(tiers=("not-a-preset",))


def test_http_tier_selection_and_iters_header(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    left, right = _pair(seed=7)
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=2, iters=ITERS, tiers=("interactive:1e9:2", "quality")))
    server = StereoHTTPServer(svc, port=0).start()
    try:
        buf = io.BytesIO()
        np.savez(buf, left=left, right=right)

        def post(url):
            req = urllib.request.Request(url, data=buf.getvalue(),
                                         method="POST")
            req.add_header("Content-Type", "application/x-npz")
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    return resp.status, dict(resp.headers), resp.read()
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), e.read()

        status, headers, _ = post(
            server.url + "/v1/disparity?tier=interactive")
        assert status == 200
        assert headers["X-Tier"] == "interactive"
        assert headers["X-Iters-Used"] == "2"
        status, headers, _ = post(server.url + "/v1/disparity")
        assert status == 200 and headers["X-Tier"] == "quality"
        assert headers["X-Iters-Used"] == str(ITERS)
        status, _, body = post(server.url + "/v1/disparity?tier=bogus")
        assert status == 400 and b"unknown tier" in body
        with urllib.request.urlopen(server.url + "/metrics",
                                    timeout=30) as resp:
            text = resp.read().decode()
        assert 'infer_gru_iters_used_count{tier="interactive"} 1' in text
    finally:
        server.shutdown()
        svc.close()
