"""Fused no-volume alt kernel (kernels/corr_alt.py) vs the XLA alt backend.

Runs the kernel in interpreter mode on CPU — the same program the TPU
compiles.  The XLA path (feature sampling + einsum) is the semantics
reference; the kernel must match it in values and feature gradients
(coords gradients are intentionally zero — RAFT detaches coords).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-model / subprocess-scale tests

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.kernels import corr_alt, corr_lookup
from raft_stereo_tpu.models.corr import make_corr_fn_alt


@pytest.fixture
def _interpret_mode():
    corr_lookup._interpret_override = True
    yield
    corr_lookup._interpret_override = None


def _xla_alt(cfg, f1, f2):
    """The REAL pure-XLA alt fallback in make_corr_fn_alt, reached by
    forcing the fused dispatch off."""
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(corr_alt, "alt_fused_available", lambda: False)
        return make_corr_fn_alt(cfg, f1, f2)


@pytest.mark.parametrize("w2", [40, 37])
def test_alt_fused_matches_xla(rng, _interpret_mode, w2):
    cfg = RaftStereoConfig(corr_backend="alt")
    b, h, w1, d = 1, 4, 24, 16
    f1 = jnp.asarray(rng.standard_normal((b, h, w1, d)), jnp.float32)
    f2 = jnp.asarray(rng.standard_normal((b, h, w2, d)), jnp.float32)
    coords = jnp.asarray(rng.uniform(-3, w2 + 3, (b, h, w1)), jnp.float32)

    ref = _xla_alt(cfg, f1, f2)(coords)
    fused = make_corr_fn_alt(cfg, f1, f2)(coords)  # dispatches to the kernel
    assert fused.shape == ref.shape
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_alt_fused_gradients_match_xla(rng, _interpret_mode):
    cfg = RaftStereoConfig(corr_backend="alt", corr_levels=2)
    b, h, w1, w2, d = 1, 3, 16, 24, 8
    f1 = jnp.asarray(rng.standard_normal((b, h, w1, d)), jnp.float32)
    f2 = jnp.asarray(rng.standard_normal((b, h, w2, d)), jnp.float32)
    coords = jnp.asarray(rng.uniform(0, w2, (b, h, w1)), jnp.float32)
    cot = jnp.asarray(rng.standard_normal(
        (b, h, w1, cfg.corr_levels * (2 * cfg.corr_radius + 1))), jnp.float32)

    def loss_ref(f1_, f2_):
        return jnp.sum(_xla_alt(cfg, f1_, f2_)(coords) * cot)

    def loss_fused(f1_, f2_):
        return jnp.sum(make_corr_fn_alt(cfg, f1_, f2_)(coords) * cot)

    g_ref = jax.grad(loss_ref, argnums=(0, 1))(f1, f2)
    g_fused = jax.grad(loss_fused, argnums=(0, 1))(f1, f2)
    for a, b_ in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


def test_alt_per_level_fallback_matches_multi(rng, _interpret_mode,
                                              monkeypatch):
    """The per-level launch path (taken at full resolution, over the VMEM
    budget) must agree with the single-launch multi-level path."""
    cfg = RaftStereoConfig(corr_backend="alt")
    b, h, w1, w2, d = 1, 4, 24, 40, 16
    f1 = jnp.asarray(rng.standard_normal((b, h, w1, d)), jnp.float32)
    f2 = jnp.asarray(rng.standard_normal((b, h, w2, d)), jnp.float32)
    coords = jnp.asarray(rng.uniform(-3, w2 + 3, (b, h, w1)), jnp.float32)

    multi = make_corr_fn_alt(cfg, f1, f2)(coords)
    # A budget big enough for 1-row blocks (alt_fused_fits stays True, the
    # kernel stays engaged) but far below the multi launch's working set ->
    # forces the per-level launch path specifically.
    monkeypatch.setattr(corr_alt, "VMEM_BUDGET", 200_000)
    monkeypatch.setattr(corr_lookup, "VMEM_BUDGET", 200_000)
    per_level = make_corr_fn_alt(cfg, f1, f2)(coords)
    np.testing.assert_array_equal(np.asarray(multi), np.asarray(per_level))

    # gradients through the per-level path too
    cot = jnp.asarray(rng.standard_normal(multi.shape), jnp.float32)
    g1 = jax.grad(lambda a: jnp.sum(make_corr_fn_alt(cfg, a, f2)(coords)
                                    * cot))(f1)
    monkeypatch.undo()
    g2 = jax.grad(lambda a: jnp.sum(make_corr_fn_alt(cfg, a, f2)(coords)
                                    * cot))(f1)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-6, atol=1e-6)


def test_alt_fused_model_forward(rng, _interpret_mode):
    """Whole model with the alt backend routes through the fused kernel in
    interpret mode and stays finite."""
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(corr_backend="alt", n_gru_layers=1,
                           hidden_dims=(32,), fnet_dim=64)
    model = RAFTStereo(cfg)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 64, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 64, 3)), jnp.float32)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1, test_mode=True)
    lo, up = model.apply(v, img1, img2, iters=2, test_mode=True)
    assert up.shape == (1, 32, 64)
    assert np.isfinite(np.asarray(up)).all()


def test_multi_alt_gate_tracks_mosaic_stack():
    """The single-launch multi-level gate models Mosaic's no-reuse stack:
    the 544x960 fp32 accuracy shape (wcat=450, d=256) measured 18.11 MiB
    scoped and FAILED to compile, so the gate must route it per-level; the
    realtime KITTI shape (bf16, wcat=292) compiles (~12 MiB) and must stay
    on the fast multi path."""
    from raft_stereo_tpu.kernels.corr_alt import (_MOSAIC_SCOPED_VMEM,
                                                  _multi_alt_scoped_bytes)

    full_fp32 = _multi_alt_scoped_bytes([240, 120, 60, 30], 256, 4, 4)
    assert full_fp32 > _MOSAIC_SCOPED_VMEM, full_fp32
    realtime_bf16 = _multi_alt_scoped_bytes([156, 78, 39, 19], 256, 2, 4)
    assert realtime_bf16 <= _MOSAIC_SCOPED_VMEM, realtime_bf16
