"""Fleet-layer tests (tier-1, CPU): the round-16 replicated-serving
story — consistent-hash routing, failover, the typed session-loss
contract, fleet-wide brownout propagation, artifact-store GC, and the
graceful-shutdown readiness flip.

Most tests run against STUB replicas — tiny stdlib HTTP servers speaking
the replica protocol (healthz/readyz/v1/* /admin/brownout) with
scriptable load and failure modes — so routing policy is exercised in
milliseconds with no JAX.  The acceptance pin (router pass-through is
byte-identical to hitting one replica directly) additionally runs
against a REAL engine at the bottom of the file.
"""

import io
import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np
import pytest

from raft_stereo_tpu.serving.fleet import (FleetRouter, HashRing,
                                           NoReplicasAvailable,
                                           RouterConfig, RouterHTTPServer,
                                           SessionLost)

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")


# ------------------------------------------------------------------- ring
def test_ring_sticky_and_deterministic():
    """Same session id -> same replica, across lookups AND across fresh
    ring instances (a router restart must not reshuffle live sessions)."""
    keys = [f"sess-{i}" for i in range(200)]
    r1 = HashRing(["a", "b", "c"])
    r2 = HashRing(["c", "a", "b"])         # insertion order irrelevant
    for k in keys:
        owner = r1.lookup(k)
        assert owner in ("a", "b", "c")
        assert r1.lookup(k) == owner        # sticky
        assert r2.lookup(k) == owner        # instance-independent


def test_ring_removal_remaps_only_the_dead_members_keys():
    """The consistent-hashing invariant (NOT mod-N): removing one of N
    replicas remaps exactly the keys it owned (~1/N), and every other
    key keeps its owner."""
    keys = [f"sess-{i}" for i in range(1200)]
    ring = HashRing(["a", "b", "c"])
    before = ring.assignment(keys)
    dead_keys = {k for k, v in before.items() if v == "b"}
    # roughly balanced: each member owns a nontrivial share
    frac = len(dead_keys) / len(keys)
    assert 0.15 < frac < 0.55, f"member share {frac:.2f} wildly skewed"
    ring.remove("b")
    after = ring.assignment(keys)
    for k in keys:
        if k in dead_keys:
            assert after[k] in ("a", "c")   # redistributed to survivors
        else:
            assert after[k] == before[k], \
                "a key not owned by the dead member must not move"
    # mod-N for contrast would have remapped ~2/3 of ALL keys; here the
    # remapped fraction IS the dead member's share.
    remapped = sum(1 for k in keys if after[k] != before[k])
    assert remapped == len(dead_keys)


def test_ring_readd_restores_original_assignment():
    keys = [f"sess-{i}" for i in range(500)]
    ring = HashRing(["a", "b", "c"])
    before = ring.assignment(keys)
    ring.remove("b")
    assert any(v == "b" for v in before.values())
    ring.add("b")
    assert ring.assignment(keys) == before, \
        "re-adding a member must restore the exact prior assignment " \
        "(member points are a pure function of the name)"


def test_ring_empty_and_single():
    ring = HashRing()
    assert ring.lookup("x") is None
    ring.add("only")
    assert all(ring.lookup(f"k{i}") == "only" for i in range(20))
    ring.remove("only")
    assert ring.lookup("x") is None


# ---------------------------------------------------------- stub replicas
class StubReplica:
    """A scriptable stand-in for one ``raft-serve`` process: speaks the
    replica HTTP protocol, records what it was asked, and can be killed
    or blackholed on demand."""

    def __init__(self, name: str, ready: bool = True,
                 queue_depth: int = 0, queue_limit: int = 64,
                 xl=None):
        self.name = name
        self.ready = ready
        self.queue_depth = queue_depth
        self.queue_limit = queue_limit
        self.blackhole_health = False
        self.xl = xl
        # Graceful-drain scripting (round 18): ``draining`` flips
        # healthz/readyz like a real SIGTERMed replica and the request
        # path sheds the typed draining 503; ``handoff_manifest`` is
        # what GET /admin/handoff serves (None -> 404, like an engine
        # that has not published yet).
        self.draining = False
        self.handoff_manifest = None
        self.requests = []
        self.sessions = []
        self.stream_headers = []
        self.stateless_headers = []
        self.brownout_levels = []
        # Observability scripting (round 23): what GET /metrics serves
        # (federation scrapes it), trace_id -> spans for GET
        # /debug/spans?trace=, and a count of coordinated
        # POST /debug/flightrecorder dumps.
        self.metrics_text = (
            "# HELP stub_requests_total Requests this stub handled.\n"
            "# TYPE stub_requests_total counter\n"
            f'stub_requests_total{{stub="{name}"}} 0\n')
        self.spans = {}
        self.flightrecorder_dumps = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, body, ctype="application/json",
                      extra=()):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code, obj, extra=()):
                self._send(code, (json.dumps(obj) + "\n").encode(),
                           extra=extra)

            def do_GET(self):
                if (outer.blackhole_health
                        and self.path in ("/healthz", "/readyz")):
                    self.close_connection = True
                    return
                if self.path == "/healthz":
                    self._json(200, {
                        "status": ("draining" if outer.draining
                                   else "ok"),
                        "ready": outer.ready and not outer.draining,
                        "queue_depth": outer.queue_depth,
                        "queue_limit": outer.queue_limit,
                        "inflight": 0, "brownout_level": 0,
                        "xl": outer.xl,
                        "sessions_active": len(set(outer.sessions))})
                elif self.path == "/readyz":
                    up = outer.ready and not outer.draining
                    self._json(200 if up else 503, {"ready": up})
                elif self.path == "/admin/handoff":
                    if outer.handoff_manifest is None:
                        self._json(404, {"error": "no_handoff"})
                    else:
                        self._json(200, outer.handoff_manifest)
                elif urlparse(self.path).path == "/metrics":
                    self._send(200, outer.metrics_text.encode(),
                               ctype="text/plain; version=0.0.4")
                elif urlparse(self.path).path == "/debug/spans":
                    q = parse_qs(urlparse(self.path).query)
                    tid = q.get("trace", [""])[0]
                    self._json(200, {"trace_id": tid,
                                     "spans": outer.spans.get(tid, [])})
                else:
                    self._json(404, {"error": "no route"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                path = urlparse(self.path).path
                outer.requests.append(("POST", self.path))
                if path == "/admin/brownout":
                    outer.brownout_levels.append(
                        json.loads(body)["level"])
                    self._json(200, {"status": "ok"})
                    return
                if path == "/debug/flightrecorder":
                    # The coordinated-dump fan-out target (round 23).
                    outer.flightrecorder_dumps += 1
                    self._json(200, {"status": "dumped",
                                     "bundle": f"/tmp/{outer.name}",
                                     "trigger": "forced"})
                    return
                if outer.draining and path.startswith("/v1/"):
                    # The engine's typed draining shed (begin_shutdown
                    # stopped admitting while the listener stays up).
                    self._json(503, {"error": "overloaded",
                                     "draining": True,
                                     "retry_after_s": 5.0},
                               extra=[("Retry-After", "5")])
                    return
                if path.startswith("/v1/stream/"):
                    sid = path[len("/v1/stream/"):]
                    outer.sessions.append(sid)
                    outer.stream_headers.append(
                        (sid, {k: v for k, v in self.headers.items()}))
                    warm = (outer.sessions.count(sid) > 1
                            or "X-Handoff-Artifact" in self.headers)
                    self._send(
                        200, b"frame:" + outer.name.encode() + body,
                        ctype="application/x-npy",
                        extra=[("X-Session-Id", sid),
                               ("X-Warm", "1" if warm else "0")])
                elif path == "/v1/disparity":
                    outer.stateless_headers.append(
                        {k: v for k, v in self.headers.items()})
                    self._send(
                        200, b"disp:" + outer.name.encode() + body,
                        ctype="application/x-npy",
                        extra=[("X-Batch-Size", "1"),
                               ("X-Iters-Used", "7")])
                else:
                    self._json(404, {"error": "no route"})

            def do_DELETE(self):
                path = urlparse(self.path).path
                outer.requests.append(("DELETE", self.path))
                if path.startswith("/v1/stream/"):
                    self._json(200, {"status": "closed", "frames": 0})
                else:
                    self._json(404, {"error": "no route"})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def kill(self):
        """Hard stop: connections start refusing (the router sees a dead
        replica)."""
        self.server.shutdown()
        self.server.server_close()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture()
def fleet3():
    stubs = [StubReplica(f"s{i}") for i in range(3)]
    router = FleetRouter(
        {s.name: s.url for s in stubs},
        RouterConfig(health_timeout_s=2.0, fail_after=1,
                     request_timeout_s=5.0, fleet_brownout=False))
    router.check_replicas()
    yield stubs, router
    for s in stubs:
        try:
            s.kill()
        except Exception:
            pass


# ----------------------------------------------------------- router core
def test_router_stateless_balances_and_counts(fleet3):
    stubs, router = fleet3
    assert router.fleet_status()["ready"] == 3
    for _ in range(9):
        status, headers, body = router.forward_stateless(
            "POST", "/v1/disparity", b"xyz", [])
        assert status == 200 and body.startswith(b"disp:s")
    hit = [len(s.requests) for s in stubs]
    assert sum(hit) == 9
    assert all(h > 0 for h in hit), \
        f"equal-load replicas should share round-robin traffic: {hit}"
    assert router.routed("stateless") == 9


def test_router_stateless_failover_zero_loss(fleet3):
    """A replica dying mid-traffic burns attempts, never requests: every
    stateless request still answers (inference is idempotent — the
    retry is safe), and the dead replica leaves the rotation."""
    stubs, router = fleet3
    stubs[0].kill()     # dies NOW; the router has not probed since
    ok = 0
    for i in range(30):
        status, _, body = router.forward_stateless(
            "POST", "/v1/disparity", f"req{i}".encode(), [])
        assert status == 200 and body.startswith(b"disp:s")
        ok += 1
    assert ok == 30, "zero stateless loss under replica death"
    assert router.failovers.value >= 1
    assert router.fleet_status()["ready"] == 2
    assert not router.replicas["s0"].alive


def test_router_sessions_sticky_then_lost_typed_then_reseed(fleet3):
    """The fleet-wide 410 contract: frames of one session always land on
    one replica; when that replica dies the session fails typed EXACTLY
    once, and the client's next frame reseeds cold on a survivor."""
    stubs, router = fleet3
    by_name = {s.name: s for s in stubs}
    sids = [f"cam-{i}" for i in range(12)]
    owner = {}
    for sid in sids:
        for _ in range(3):                      # three frames each
            status, headers, body = router.forward_session(
                sid, "POST", f"/v1/stream/{sid}", b"f", [])
            assert status == 200
        homes = {name for name, s in by_name.items()
                 if sid in s.sessions}
        assert len(homes) == 1, \
            f"session {sid} touched {homes}: stickiness broken"
        owner[sid] = homes.pop()
    victim_name = owner[sids[0]]
    lost_sids = [s for s in sids if owner[s] == victim_name]
    survivors = [s for s in sids if owner[s] != victim_name]
    by_name[victim_name].kill()
    # First frame after the death: transport failure -> typed loss.
    with pytest.raises(SessionLost) as e:
        router.forward_session(lost_sids[0], "POST",
                               f"/v1/stream/{lost_sids[0]}", b"f", [])
    assert e.value.replica == victim_name
    assert router.sessions_lost.value >= 1
    # Other sessions of the dead replica were tombstoned by the death:
    # their next frame fails typed WITHOUT another transport attempt.
    for sid in lost_sids[1:]:
        with pytest.raises(SessionLost):
            router.forward_session(sid, "POST", f"/v1/stream/{sid}",
                                   b"f", [])
    # Fire-once: the SAME ids now reseed cold on a surviving replica.
    for sid in lost_sids:
        status, _, _ = router.forward_session(
            sid, "POST", f"/v1/stream/{sid}", b"f", [])
        assert status == 200
        new_home = {n for n, s in by_name.items()
                    if n != victim_name and sid in s.sessions}
        assert len(new_home) == 1
    # Sessions on survivors never noticed.
    for sid in survivors:
        status, _, _ = router.forward_session(
            sid, "POST", f"/v1/stream/{sid}", b"f", [])
        assert status == 200


def test_router_remap_fraction_on_death_is_about_one_nth(fleet3):
    """Ring-level blast radius through the router: replica death loses
    ~1/3 of routed sessions, not all of them."""
    stubs, router = fleet3
    sids = [f"cam-{i}" for i in range(120)]
    for sid in sids:
        router.forward_session(sid, "POST", f"/v1/stream/{sid}", b"f", [])
    victim = stubs[1]
    owned = [sid for sid in sids if sid in victim.sessions]
    victim.kill()
    router.check_replicas()       # probe pass notices the death
    status = router.fleet_status()
    assert status["ready"] == 2
    assert status["sessions_pending_loss"] == len(owned)
    frac = len(owned) / len(sids)
    assert 0.15 < frac < 0.55


def test_router_health_blackhole_counts_as_dead(fleet3):
    """A replica whose /healthz stops answering (connection closed, no
    response) while its request path still works must leave the
    rotation: a zombie to the balancer is dead to the balancer."""
    stubs, router = fleet3
    stubs[2].blackhole_health = True
    router.check_replicas()       # fail_after=1 -> out immediately
    assert router.fleet_status()["ready"] == 2
    assert "s2" not in router.ring.members
    # recovery: probes answering again put it back
    stubs[2].blackhole_health = False
    router.check_replicas()
    assert router.fleet_status()["ready"] == 3


def test_router_not_ready_replica_out_of_rotation(fleet3):
    stubs, router = fleet3
    stubs[1].ready = False        # warming / draining: alive, not ready
    router.check_replicas()
    assert router.fleet_status()["ready"] == 2
    for _ in range(6):
        _, _, body = router.forward_stateless("POST", "/v1/disparity",
                                              b"x", [])
        assert not body.startswith(b"disp:s1")
    stubs[1].ready = True
    router.check_replicas()
    assert router.fleet_status()["ready"] == 3


def test_router_all_dead_typed_no_replicas(fleet3):
    stubs, router = fleet3
    for s in stubs:
        s.kill()
    for _ in range(2):
        router.check_replicas()
    with pytest.raises(NoReplicasAvailable):
        router.forward_stateless("POST", "/v1/disparity", b"x", [])
    assert router.unroutable.value >= 1


def test_router_brownout_propagates_fleet_wide():
    """Sustained AGGREGATE pressure pushes one brownout floor to every
    replica (lockstep degradation); sustained calm restores it."""
    stubs = [StubReplica(f"s{i}", queue_depth=60, queue_limit=64)
             for i in range(3)]
    clock = FakeClock()
    router = FleetRouter(
        {s.name: s.url for s in stubs},
        RouterConfig(health_timeout_s=2.0, fleet_brownout=True,
                     brownout_engage_s=0.5, brownout_restore_s=1.0,
                     brownout_max_level=2),
        clock=clock)
    try:
        router.check_replicas()          # pressure_since arms
        clock.t += 0.6
        router.check_replicas()          # sustained -> level 1, pushed
        assert router.brownout_level == 1
        for s in stubs:
            assert s.brownout_levels[-1:] == [1], \
                f"{s.name} never got the fleet floor: {s.brownout_levels}"
        clock.t += 0.6
        router.check_replicas()          # next rung needs its own window
        assert router.brownout_level == 2
        # calm: pressure gone, restore after the longer calm window
        for s in stubs:
            s.queue_depth = 0
        router.check_replicas()
        clock.t += 1.1
        router.check_replicas()
        assert router.brownout_level == 1
        assert all(s.brownout_levels[-1] == 1 for s in stubs)
    finally:
        for s in stubs:
            s.kill()


# ------------------------------------------------- drain handoff (round 18)
def _route_sessions(router, stubs, n=12):
    """Open n sessions through the router; returns {sid: owner_name}."""
    owner = {}
    by_name = {s.name: s for s in stubs}
    for i in range(n):
        sid = f"cam-{i}"
        router.forward_session(sid, "POST", f"/v1/stream/{sid}", b"f", [])
        owner[sid] = next(name for name, s in by_name.items()
                          if sid in s.sessions)
    return owner


def test_drain_handoff_remaps_sessions_zero_410(fleet3):
    """The round-18 acceptance shape at routing level: a replica that
    DRAINS (instead of dying) hands its sessions to survivors — zero
    SessionLost, every inherited frame tagged with the handoff
    artifact, and the tag consumed after the first 200."""
    stubs, router = fleet3
    owner = _route_sessions(router, stubs)
    victim = next(s for s in stubs
                  if any(o == s.name for o in owner.values()))
    moved = [sid for sid, o in owner.items() if o == victim.name]
    kept = [sid for sid, o in owner.items() if o != victim.name]
    victim.draining = True
    victim.handoff_manifest = {"artifact": "abc123", "sessions": moved,
                               "count": len(moved)}
    router.check_replicas()      # drain observed + manifest fetched
    st = router.fleet_status()
    assert st["ready"] == 2
    assert st["sessions_pending_loss"] == 0, \
        "a planned drain must not type its sessions lost"
    assert st["sessions_pending_handoff"] == len(moved)
    assert router.sessions_lost.value == 0
    # Every moved session's next frame: 200 on a survivor, tagged.
    for sid in moved:
        status, headers, body = router.forward_session(
            sid, "POST", f"/v1/stream/{sid}", b"f", [])
        assert status == 200
        assert not body.startswith(b"frame:" + victim.name.encode())
        tagged = [h for s2, h in
                  [e for st2 in stubs for e in st2.stream_headers]
                  if s2 == sid and "X-Handoff-Artifact" in h]
        assert tagged and tagged[-1]["X-Handoff-Artifact"] == "abc123"
    assert router.fleet_status()["sessions_pending_handoff"] == 0, \
        "the handoff tag is consumed by the first successful frame"
    # Second frame: no tag (the survivor owns the live state now).
    for sid in moved[:2]:
        status, _, _ = router.forward_session(
            sid, "POST", f"/v1/stream/{sid}", b"f", [])
        assert status == 200
    # Survivor-owned sessions never noticed.
    for sid in kept:
        status, _, _ = router.forward_session(
            sid, "POST", f"/v1/stream/{sid}", b"f", [])
        assert status == 200
    assert router.sessions_lost.value == 0
    assert router.handoff_sessions.value == len(moved)


def test_drain_503_race_rerouted_inline(fleet3):
    """A frame that reaches a draining replica BEFORE the router's next
    probe gets the typed draining 503 — the router must treat that as
    the drain signal, fetch the manifest, and retry the frame once on
    the inheriting replica.  Zero client-visible failures."""
    stubs, router = fleet3
    owner = _route_sessions(router, stubs, n=8)
    victim = next(s for s in stubs
                  if any(o == s.name for o in owner.values()))
    moved = [sid for sid, o in owner.items() if o == victim.name]
    # Drain flips WITHOUT a probe pass: the router still routes there.
    victim.draining = True
    victim.handoff_manifest = {"artifact": "race-key",
                               "sessions": moved, "count": len(moved)}
    sid = moved[0]
    status, headers, body = router.forward_session(
        sid, "POST", f"/v1/stream/{sid}", b"f", [])
    assert status == 200, "the race must be absorbed, not surfaced"
    assert not body.startswith(b"frame:" + victim.name.encode())
    assert router.sessions_lost.value == 0
    assert victim.name not in router.ring.members


def test_drain_without_manifest_falls_back_to_typed_loss(fleet3):
    """A drain that never publishes (crash mid-drain, pre-r18 replica)
    keeps the r16 contract: when the process goes away its sessions
    fail typed, exactly once."""
    stubs, router = fleet3
    owner = _route_sessions(router, stubs, n=8)
    victim = next(s for s in stubs
                  if any(o == s.name for o in owner.values()))
    moved = [sid for sid, o in owner.items() if o == victim.name]
    victim.draining = True       # manifest stays 404
    router.check_replicas()
    assert router.fleet_status()["sessions_pending_loss"] == 0
    victim.kill()                # dies before ever publishing
    router.check_replicas()
    router.check_replicas()
    assert router.fleet_status()["sessions_pending_loss"] == len(moved)
    with pytest.raises(SessionLost):
        router.forward_session(moved[0], "POST",
                               f"/v1/stream/{moved[0]}", b"f", [])
    status, _, _ = router.forward_session(       # fire-once: reseeds
        moved[0], "POST", f"/v1/stream/{moved[0]}", b"f", [])
    assert status == 200


def test_lost_ledger_bounded_by_cap_and_gauge():
    """Satellite: the lost-session ledger is capacity-capped like the
    SessionStore tombstones, with fleet_lost_ledger_size live."""
    stubs = [StubReplica(f"s{i}") for i in range(2)]
    router = FleetRouter(
        {s.name: s.url for s in stubs},
        RouterConfig(health_timeout_s=2.0, fail_after=1,
                     request_timeout_s=5.0, fleet_brownout=False,
                     session_lost_cap=5))
    try:
        router.check_replicas()
        owner = _route_sessions(router, stubs, n=20)
        victim = next(s for s in stubs
                      if sum(1 for o in owner.values()
                             if o == s.name) > 5)
        n_owned = sum(1 for o in owner.values() if o == victim.name)
        victim.kill()
        router.check_replicas()
        st = router.fleet_status()
        assert n_owned > 5
        assert st["sessions_pending_loss"] == 5, \
            "the cap must forget the oldest owed 410s"
        assert router.lost_ledger_size.value == 5
        # firing one decrements the gauge
        fired = [sid for sid, o in owner.items()
                 if o == victim.name][-1]
        with pytest.raises(SessionLost):
            router.forward_session(fired, "POST",
                                   f"/v1/stream/{fired}", b"f", [])
        assert router.lost_ledger_size.value == 4
    finally:
        for s in stubs:
            try:
                s.kill()
            except Exception:
                pass


# --------------------------------------------------- xl-capability routing
def test_xl_routing_heterogeneous_fleet():
    """``?tier=xl`` requests land only on replicas advertising the mesh
    tier; plain requests still balance over everyone; a fleet whose xl
    replicas all left rotation answers the typed xl_unavailable."""
    from raft_stereo_tpu.serving.fleet import XlUnavailable

    xl_topo = {"mesh": "rows=4", "label": "rows4", "groups": 1,
               "devices_per_group": 4, "threshold_pixels": 2_000_000,
               "batch_sizes": [1]}
    stubs = [StubReplica("plain0"), StubReplica("plain1"),
             StubReplica("big0", xl=xl_topo)]
    router = FleetRouter(
        {s.name: s.url for s in stubs},
        RouterConfig(health_timeout_s=2.0, fail_after=1,
                     request_timeout_s=5.0, fleet_brownout=False))
    try:
        router.check_replicas()
        for _ in range(6):
            status, _, body = router.forward_stateless(
                "POST", "/v1/disparity?tier=xl", b"big", [])
            assert status == 200 and body.startswith(b"disp:big0"), \
                "xl requests must route to the xl-capable replica"
        # the X-Tier header spelling routes identically
        status, _, body = router.forward_stateless(
            "POST", "/v1/disparity", b"big", [("X-Tier", "xl")])
        assert body.startswith(b"disp:big0")
        # non-xl traffic is unconstrained
        hit = set()
        for _ in range(12):
            _, _, body = router.forward_stateless(
                "POST", "/v1/disparity", b"x", [])
            hit.add(body.split(b":")[1][:6])
        assert len(hit) > 1
        # xl replica leaves rotation -> typed 503 with the counts
        stubs[2].kill()
        router.check_replicas()
        with pytest.raises(XlUnavailable) as e:
            router.forward_stateless("POST", "/v1/disparity?tier=xl",
                                     b"big", [])
        assert e.value.capable_ready == 0
        assert router.xl_unroutable.value >= 1
        # plain traffic still flows
        status, _, _ = router.forward_stateless("POST", "/v1/disparity",
                                                b"x", [])
        assert status == 200
    finally:
        for s in stubs:
            try:
                s.kill()
            except Exception:
                pass


def test_xl_unavailable_typed_over_http(fleet3):
    stubs, router = fleet3          # nobody advertises xl
    server = RouterHTTPServer(router, port=0).start()
    try:
        status, headers, body = _post(
            f"{server.url}/v1/disparity?tier=xl", b"big")
        assert status == 503
        err = json.loads(body)
        assert err["error"] == "xl_unavailable"
        assert err["capable_replicas"] == 0
        assert "Retry-After" in headers
        assert 0.5 <= err["retry_after_s"] <= 1.5
    finally:
        server.shutdown()


# --------------------------------------------------------- HA ledger + pair
def test_ledger_fencing_rejects_stale_writer(tmp_path):
    from raft_stereo_tpu.serving.fleet import FleetLedger

    a = FleetLedger(str(tmp_path), "rt-a")
    b = FleetLedger(str(tmp_path), "rt-b")
    assert a.acquire() == 1
    assert a.append("lost", sids=["s1"], replica="r0")
    assert b.acquire() == 2, "takeover bumps the fencing epoch"
    assert b.append("fired", sid="s1")
    # the stale writer's appends are REJECTED, not interleaved
    assert a.append("fired", sid="s2") is False
    assert a.rejected_appends == 1
    assert not a.active, "a fenced writer knows it lost the lease"
    kinds = [r["kind"] for r in b.replay()]
    assert kinds == ["lost", "fired"], \
        "the stale append must not have reached the ledger"
    # renew() on the fenced writer also reports the loss
    assert a.renew() is False
    assert b.renew() is True


def test_ledger_replay_skips_torn_tail(tmp_path):
    from raft_stereo_tpu.serving.fleet import FleetLedger

    a = FleetLedger(str(tmp_path), "rt-a")
    a.acquire()
    a.append("lost", sids=["x"], replica="r0")
    with open(a._ledger_path, "a") as f:
        f.write('{"kind": "lost", "sids": ["torn...')   # torn tail
    assert [r["kind"] for r in a.replay()] == ["lost"]


def test_ledger_lease_staleness(tmp_path):
    from raft_stereo_tpu.serving.fleet import FleetLedger

    clock = FakeClock(t=100.0)
    a = FleetLedger(str(tmp_path), "rt-a", clock=clock)
    b = FleetLedger(str(tmp_path), "rt-b", clock=clock)
    a.acquire()
    assert not b.is_stale(3.0)
    clock.t += 5.0
    assert b.is_stale(3.0), "an unrenewed lease goes stale"
    a.renew()
    assert not b.is_stale(3.0)
    assert not a.is_stale(3.0), "the holder never sees itself stale"


def test_ha_takeover_never_double_fires_a_loss(tmp_path):
    """The acceptance pin: a loss FIRED by the primary is never fired
    again by the standby after takeover (the ledger's fired record
    survives the router's death); a loss OWED but not yet delivered
    re-arms and fires exactly once on the standby."""
    stubs = [StubReplica(f"s{i}") for i in range(3)]
    ha = str(tmp_path)
    cfg = dict(health_timeout_s=2.0, fail_after=1,
               request_timeout_s=5.0, fleet_brownout=False)
    primary = FleetRouter({s.name: s.url for s in stubs},
                          RouterConfig(ha_dir=ha, router_name="rt-a",
                                       **cfg))
    standby = None
    try:
        assert primary.active and primary.ledger.epoch == 1
        primary.check_replicas()
        owner = _route_sessions(primary, stubs, n=10)
        victim = next(s for s in stubs
                      if sum(1 for o in owner.values()
                             if o == s.name) >= 2)
        lost = [sid for sid, o in owner.items() if o == victim.name]
        victim.kill()
        primary.check_replicas()
        # primary delivers ONE of the owed 410s, then "dies"
        with pytest.raises(SessionLost):
            primary.forward_session(lost[0], "POST",
                                    f"/v1/stream/{lost[0]}", b"f", [])
        standby = FleetRouter({s.name: s.url for s in stubs},
                              RouterConfig(ha_dir=ha,
                                           router_name="rt-b",
                                           standby=True, **cfg))
        assert not standby.active
        standby.check_replicas()
        standby.takeover()
        assert standby.active and standby.ledger.epoch == 2
        # the fired id must NOT fire again: it reseeds cold instead
        status, _, _ = standby.forward_session(
            lost[0], "POST", f"/v1/stream/{lost[0]}", b"f", [])
        assert status == 200, \
            "a 410 already delivered must never fire twice for one id"
        # an owed-but-undelivered id fires exactly once on the standby
        with pytest.raises(SessionLost):
            standby.forward_session(lost[1], "POST",
                                    f"/v1/stream/{lost[1]}", b"f", [])
        status, _, _ = standby.forward_session(
            lost[1], "POST", f"/v1/stream/{lost[1]}", b"f", [])
        assert status == 200
        # the fenced ex-primary can no longer append
        assert primary._ledger_append("fired", sid="zzz") is False
        assert not primary.active, "fencing demotes the stale primary"
    finally:
        primary.stop()
        if standby is not None:
            standby.stop()
        for s in stubs:
            try:
                s.kill()
            except Exception:
                pass


def test_ha_standby_serves_while_passive(tmp_path):
    """The standby forwards traffic the whole time (stateless balancing
    and ring-sticky sessions need no shared state) — only ledger writes
    wait for the lease."""
    stubs = [StubReplica(f"s{i}") for i in range(2)]
    cfg = dict(health_timeout_s=2.0, fail_after=1,
               request_timeout_s=5.0, fleet_brownout=False)
    primary = FleetRouter({s.name: s.url for s in stubs},
                          RouterConfig(ha_dir=str(tmp_path),
                                       router_name="rt-a", **cfg))
    standby = FleetRouter({s.name: s.url for s in stubs},
                          RouterConfig(ha_dir=str(tmp_path),
                                       router_name="rt-b",
                                       standby=True, **cfg))
    try:
        primary.check_replicas()
        standby.check_replicas()
        assert standby.fleet_status()["role"] == "standby"
        status, _, _ = standby.forward_stateless(
            "POST", "/v1/disparity", b"x", [])
        assert status == 200
        # both routers agree on session placement (deterministic ring)
        for sid in ("cam-a", "cam-b", "cam-c"):
            assert (primary.ring.lookup(sid)
                    == standby.ring.lookup(sid))
    finally:
        primary.stop()
        standby.stop()
        for s in stubs:
            try:
                s.kill()
            except Exception:
                pass


# ------------------------------------------------------------- autoscaler
class RecordingLauncher:
    """Scripted ReplicaLauncher: launches are stub replicas, drains are
    recorded and complete on demand — never a kill."""

    def __init__(self):
        self.stubs = {}
        self.drained = []
        self.killed = []
        self.exited = {}

    def launch(self, name):
        stub = StubReplica(name)
        self.stubs[name] = stub
        return stub.url

    def drain(self, name):
        self.drained.append(name)
        stub = self.stubs.get(name)
        if stub is not None:
            stub.draining = True
            stub.handoff_manifest = {"artifact": None, "sessions": [],
                                     "count": 0}

    def finish_drain(self, name):
        self.exited[name] = 0
        stub = self.stubs.get(name)
        if stub is not None:
            stub.kill()

    def poll(self, name):
        return self.exited.get(name)

    def destroy(self, name):
        self.killed.append(name)
        stub = self.stubs.pop(name, None)
        if stub is not None:
            try:
                stub.kill()
            except Exception:
                pass

    def cleanup(self):
        for name in list(self.stubs):
            self.destroy(name)


def _autoscaler(router, launcher, clock, trace):
    from raft_stereo_tpu.serving.fleet import AutoscaleConfig, Autoscaler

    it = iter(trace)

    def pressure():
        try:
            return next(it)
        except StopIteration:
            return trace[-1]

    return Autoscaler(
        router, launcher,
        AutoscaleConfig(min_replicas=1, max_replicas=3,
                        engage_fraction=0.6, engage_s=1.0,
                        restore_fraction=0.15, restore_s=2.0,
                        cooldown_s=0.5),
        clock=clock, pressure_fn=pressure)


def test_autoscaler_hysteresis_on_scripted_trace():
    """Satellite: engage needs SUSTAINED pressure, the dead band holds
    (no flapping), restore needs longer sustained calm, and scale-down
    always DRAINS the launched replica."""
    base = StubReplica("base0")
    router = FleetRouter(
        {"base0": base.url},
        RouterConfig(health_timeout_s=2.0, fail_after=1,
                     request_timeout_s=5.0, fleet_brownout=False))
    launcher = RecordingLauncher()
    clock = FakeClock(t=0.0)
    # scripted pressure: spike (not sustained) -> calm -> sustained
    # spike -> dead band -> sustained calm
    trace = [0.9, 0.1,                 # blip: must NOT scale
             0.9, 0.9, 0.9,           # sustained: scale up once
             0.4, 0.4,                # dead band: hold
             0.05, 0.05, 0.05, 0.05, 0.05, 0.05]   # calm: scale down
    try:
        router.check_replicas()
        scaler = _autoscaler(router, launcher, clock, trace)
        actions = []
        for _ in range(len(trace)):
            actions.append(scaler.check())
            clock.t += 0.6
        assert actions.count("up") == 1, f"flapped: {actions}"
        assert actions.count("down") == 1, f"flapped: {actions}"
        assert actions[0] is None and actions[1] is None, \
            "a one-poll blip must not scale (engage_s hysteresis)"
        up_i = actions.index("up")
        down_i = actions.index("down")
        assert up_i < down_i
        assert launcher.drained == ["auto1"], \
            "scale-down must DRAIN the launched replica"
        assert launcher.killed == [], "scale-down must never kill"
        assert "auto1" in router.replicas, \
            "deregistration waits for the drain to finish"
        # drain completes -> reaped on the next check
        launcher.finish_drain("auto1")
        scaler.check()
        assert "auto1" not in router.replicas
        assert scaler.draining == []
        assert scaler.scale_ups.value == 1
        assert scaler.scale_downs.value == 1
    finally:
        launcher.cleanup()
        try:
            base.kill()
        except Exception:
            pass


def test_autoscaler_respects_bounds_and_cooldown():
    base = StubReplica("base0")
    router = FleetRouter(
        {"base0": base.url},
        RouterConfig(health_timeout_s=2.0, fail_after=1,
                     request_timeout_s=5.0, fleet_brownout=False))
    launcher = RecordingLauncher()
    clock = FakeClock(t=0.0)
    trace = [0.95] * 40
    try:
        router.check_replicas()
        scaler = _autoscaler(router, launcher, clock, trace)
        ups = 0
        for _ in range(40):
            if scaler.check() == "up":
                ups += 1
            clock.t += 0.4
        assert ups == 2, "max_replicas=3 bounds growth to +2"
        assert len(router.replicas) == 3
        # endless calm drains only what the autoscaler launched (the
        # base fleet stays; min_replicas is a floor, not a target)
        scaler._pressure_fn = lambda: 0.0
        downs = 0
        for _ in range(50):
            if scaler.check() == "down":
                downs += 1
            clock.t += 0.4
        assert downs == 2, "launched replicas only; base fleet stays"
        assert launcher.killed == []
    finally:
        launcher.cleanup()
        try:
            base.kill()
        except Exception:
            pass


# ---------------------------------------------------- router HTTP surface
def _get(url, timeout=5):
    req = urllib.request.Request(url)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _post(url, data, headers=None, timeout=10):
    req = urllib.request.Request(url, data=data, method="POST",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_router_http_surface_and_passthrough(fleet3):
    stubs, router = fleet3
    server = RouterHTTPServer(router, port=0).start()
    try:
        base = server.url
        status, _, body = _get(f"{base}/healthz")
        h = json.loads(body)
        assert status == 200 and h["ready_replicas"] == 3
        status, _, body = _get(f"{base}/readyz")
        assert status == 200 and json.loads(body)["ready"]
        status, _, body = _get(f"{base}/fleet")
        assert status == 200 and len(json.loads(body)["replicas"]) == 3
        status, _, body = _get(f"{base}/metrics")
        assert status == 200 and b"fleet_replicas_ready" in body
        status, _, _ = _get(f"{base}/nope")
        assert status == 404

        # Pass-through parity: same POST direct vs via router must be
        # byte-identical (body) with the same application headers.
        payload = b"\x00\x01stereo-pair-bytes\xff"
        d_status, d_headers, d_body = _post(
            f"{stubs[0].url}/v1/disparity?format=npy", payload,
            {"Content-Type": "application/x-npz"})
        # pin the router onto the same stub: kill the other two
        stubs[1].kill()
        stubs[2].kill()
        router.check_replicas()
        router.check_replicas()
        r_status, r_headers, r_body = _post(
            f"{base}/v1/disparity?format=npy", payload,
            {"Content-Type": "application/x-npz"})
        assert (r_status, r_body) == (d_status, d_body), \
            "router must be pass-through byte-identical"
        drop = {"server", "date"}
        assert ({k.lower(): v for k, v in d_headers.items()
                 if k.lower() not in drop}
                == {k.lower(): v for k, v in r_headers.items()
                    if k.lower() not in drop})

        # stream routing + typed fleet errors over HTTP
        status, headers, body = _post(f"{base}/v1/stream/cam-a", b"f")
        assert status == 200 and headers["X-Session-Id"] == "cam-a"
        stubs[0].kill()
        router.check_replicas()
        router.check_replicas()
        status, _, body = _post(f"{base}/v1/stream/cam-a", b"f")
        assert status == 410
        assert json.loads(body)["error"] == "session_lost"
        status, headers, body = _post(f"{base}/v1/disparity", b"x")
        assert status == 503
        err = json.loads(body)
        assert err["error"] == "no_replicas_ready"
        # r13 overload contract + jitter (round 18): the body carries a
        # precise jittered retry_after_s, the header its integer
        # ceiling — synchronized clients must not retry in lockstep.
        assert 0.5 <= err["retry_after_s"] <= 1.5
        assert headers["Retry-After"] in ("1", "2")
    finally:
        server.shutdown()


# ------------------------------------------------------ artifact store GC
def _fake_entry(cache, key, size, age_s):
    """Plant a fake .jaxexe entry with a controlled atime."""
    path = cache._path(key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(b"x" * size)
    old = time.time() - age_s
    os.utime(path, (old, old))
    return path


def test_disk_cache_gc_evicts_lru_by_atime(tmp_path):
    from raft_stereo_tpu.serving.persist import ExecutableDiskCache

    class G:
        value = None

        def set(self, v):
            self.value = v

    gauge = G()
    cache = ExecutableDiskCache(str(tmp_path), max_bytes=2500,
                                bytes_gauge=gauge)
    keys = [f"{i:02x}" + "ab" * 31 for i in range(4)]   # 64-hex keys
    paths = [_fake_entry(cache, k, 1000, age_s=100 - 30 * i)
             for i, k in enumerate(keys)]               # [0] oldest
    assert cache.total_bytes() == 4000
    evicted = cache.gc()
    assert evicted == 2, "4000 -> 2500 budget needs the 2 oldest gone"
    assert not os.path.exists(paths[0]) and not os.path.exists(paths[1])
    assert os.path.exists(paths[2]) and os.path.exists(paths[3])
    assert gauge.value == 2000
    assert cache.stats()["evictions"] == 2


def test_disk_cache_gc_unbounded_only_updates_gauge(tmp_path):
    from raft_stereo_tpu.serving.persist import ExecutableDiskCache

    cache = ExecutableDiskCache(str(tmp_path))
    _fake_entry(cache, "cd" * 32, 512, age_s=10)
    assert cache.gc() == 0
    assert cache.total_bytes() == 512


def test_disk_cache_read_only_never_writes_or_evicts(tmp_path):
    from raft_stereo_tpu.serving.persist import ExecutableDiskCache

    seed = ExecutableDiskCache(str(tmp_path))
    p = _fake_entry(seed, "ef" * 32, 4000, age_s=10)
    ro = ExecutableDiskCache(str(tmp_path), max_bytes=100,
                             read_only=True)
    assert ro.store("ab" * 32, object()) is False
    assert ro.gc() == 0 and os.path.exists(p), \
        "a read-only replica must never mutate the shared store"


def test_disk_cache_corrupt_and_legacy_entries_degrade_to_miss(tmp_path):
    from raft_stereo_tpu.serving.persist import ExecutableDiskCache

    cache = ExecutableDiskCache(str(tmp_path))
    key = "12" * 32
    _fake_entry(cache, key, 64, age_s=1)        # garbage bytes, sharded
    assert cache.load(key) is None              # unpickleable -> miss
    legacy_key = "34" * 32
    with open(os.path.join(str(tmp_path),
                           f"{legacy_key}.jaxexe"), "wb") as f:
        f.write(b"garbage")                     # flat round-13 layout
    assert cache.load(legacy_key) is None       # found, corrupt -> miss
    assert cache.stats()["misses"] == 2
    assert cache.load("56" * 32) is None        # absent -> miss
    assert cache.stats()["misses"] == 3


# ------------------------------------------------------ replica chaos unit
def test_chaos_die_after_is_deterministic():
    from raft_stereo_tpu.serving.chaos import ChaosConfig, ChaosInjector

    exits = []
    inj = ChaosInjector(ChaosConfig(die_after_dispatches=3),
                        exit_fn=exits.append)
    inj.on_dispatch(0)
    inj.on_dispatch(0)
    assert exits == []
    inj.on_dispatch(0)
    assert exits == [137], "the Nth dispatch kills the process, kill -9 " \
                           "style (exit code 137)"
    inj.on_dispatch(0)
    assert exits == [137]       # fires once


def test_chaos_blackhole_and_slow_start_windows():
    from raft_stereo_tpu.serving.chaos import ChaosConfig, ChaosInjector

    clock = FakeClock(t=0.0)
    inj = ChaosInjector(
        ChaosConfig(healthz_blackhole_after_s=5.0, slow_start_s=2.0),
        clock=clock)
    assert inj.ready_blocked() and not inj.blackhole()
    clock.t = 2.5
    assert not inj.ready_blocked() and not inj.blackhole()
    clock.t = 5.5
    assert inj.blackhole()


def test_chaos_spec_parses_replica_level_keys():
    from raft_stereo_tpu.serving.chaos import parse_chaos_spec

    cfg = parse_chaos_spec("die_after=7,blackhole_after_s=3,"
                           "slow_start_s=1.5")
    assert cfg.die_after_dispatches == 7
    assert cfg.healthz_blackhole_after_s == 3.0
    assert cfg.slow_start_s == 1.5
    assert cfg.enabled


# --------------------------------------------- real engine: shutdown + http
@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


def _pair(hw=(48, 64), seed=3):
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 255, hw + (3,), dtype=np.uint8)
    return left, np.roll(left, -3, axis=1)


def test_graceful_shutdown_flips_ready_and_drains(tiny_model):
    """Satellite: SIGTERM phase 1 (engine.begin_shutdown) flips the
    readiness gate (router out-of-rotation signal) and refuses new work
    typed, while already-admitted work still completes; drain() then
    finishes clean."""
    from raft_stereo_tpu.serving import (Overloaded, ServeConfig,
                                         StereoService)

    cfg, variables = tiny_model
    left, right = _pair()
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=1, batch_sizes=(1,),
                                    iters=1))
    try:
        assert svc.ready                      # no warm surface declared
        svc.queue.pause()                     # hold the queue: work is
        fut = svc.submit(left, right)         # admitted, not dispatched
        svc.begin_shutdown()
        assert not svc.ready, \
            "/readyz must flip 503 the moment shutdown begins"
        assert svc.warm_status()["draining"]
        with pytest.raises(Overloaded) as e:
            svc.submit(left, right)
        assert e.value.draining
        svc.queue.resume()
        res = fut.result(timeout=300)         # admitted work still lands
        assert res.flow.shape == left.shape[:2]
        assert svc.drain(timeout=300)
    finally:
        svc.close()


def test_admin_brownout_endpoint_and_queue_limit(tiny_model):
    """POST /admin/brownout sets the fleet floor (requests degrade with
    no local pressure at all) and /healthz reports queue_limit — the
    signals the fleet router needs from every replica."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    left, right = _pair()
    svc = StereoService(
        cfg, variables,
        ServeConfig(max_batch=1, batch_sizes=(1,), iters=1,
                    tiers=("interactive", "quality"),
                    default_tier="quality", brownout=True,
                    brownout_poll_s=5.0))   # poll too slow to interfere
    server = StereoHTTPServer(svc, port=0).start()
    try:
        status, _, body = _get(f"{server.url}/healthz")
        h = json.loads(body)
        assert status == 200 and h["queue_limit"] == 64
        status, _, body = _post(
            f"{server.url}/admin/brownout",
            json.dumps({"level": 1}).encode(),
            {"Content-Type": "application/json"})
        assert status == 200 and json.loads(body)["level"] == 1
        res = svc.infer(left, right, tier="quality", timeout=300)
        assert res.tier == "interactive" and res.degraded, \
            "the pushed floor must degrade with zero local pressure"
        status, _, body = _get(f"{server.url}/healthz")
        assert json.loads(body)["brownout_level"] == 1
        # restore
        status, _, body = _post(
            f"{server.url}/admin/brownout",
            json.dumps({"level": 0}).encode(),
            {"Content-Type": "application/json"})
        assert status == 200 and json.loads(body)["level"] == 0
        res = svc.infer(left, right, tier="quality", timeout=300)
        assert res.tier == "quality" and not res.degraded
        # malformed body
        status, _, body = _post(f"{server.url}/admin/brownout", b"{}",
                                {"Content-Type": "application/json"})
        assert status == 400
    finally:
        server.shutdown()
        svc.close()


def test_admin_brownout_unavailable_without_controller(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=1, batch_sizes=(1,),
                                    iters=1))
    server = StereoHTTPServer(svc, port=0).start()
    try:
        status, _, body = _post(
            f"{server.url}/admin/brownout",
            json.dumps({"level": 1}).encode(),
            {"Content-Type": "application/json"})
        assert status == 409
        assert json.loads(body)["error"] == "brownout_unavailable"
    finally:
        server.shutdown()
        svc.close()


def test_router_passthrough_byte_identical_real_engine(tiny_model):
    """ISSUE acceptance: with chaos off, hitting the fleet router is
    byte-identical to hitting the single replica directly — the bitwise
    solo-parity contract survives the routing layer."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    left, right = _pair(seed=11)
    buf = io.BytesIO()
    np.savez(buf, left=left, right=right)
    payload = buf.getvalue()
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=1, batch_sizes=(1,),
                                    iters=1))
    server = StereoHTTPServer(svc, port=0).start()
    router = FleetRouter({"r0": server.url},
                         RouterConfig(health_timeout_s=5.0,
                                      fleet_brownout=False))
    router.check_replicas()
    rserver = RouterHTTPServer(router, port=0).start()
    try:
        d_status, d_headers, d_body = _post(
            f"{server.url}/v1/disparity", payload,
            {"Content-Type": "application/x-npz"}, timeout=300)
        r_status, r_headers, r_body = _post(
            f"{rserver.url}/v1/disparity", payload,
            {"Content-Type": "application/x-npz"}, timeout=300)
        assert d_status == r_status == 200
        assert d_body == r_body, \
            "routed disparity bytes must equal the direct response"
        # Headers match apart from the per-request timing measurements
        # (two separate dispatches legitimately clock differently).
        drop = {"server", "date", "x-queue-wait-ms", "x-device-ms"}
        assert ({k.lower(): v for k, v in d_headers.items()
                 if k.lower() not in drop}
                == {k.lower(): v for k, v in r_headers.items()
                    if k.lower() not in drop})
        # the streaming path, routed: typed session headers intact
        s_status, s_headers, s_body = _post(
            f"{rserver.url}/v1/stream/cam-1", payload,
            {"Content-Type": "application/x-npz"}, timeout=300)
        assert s_status == 400     # engine runs without sessions: typed
        assert json.loads(s_body)["error"] == "sessions_disabled"
    finally:
        rserver.shutdown()
        router.stop()
        server.shutdown()
        svc.close()
