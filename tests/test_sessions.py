"""Streaming-session tests (tier-1, CPU): the round-14 warm-start video
layer.

Store tests run against the bare ``SessionStore`` — no JAX — so TTL
expiry, LRU eviction, tombstone semantics, and concurrency are exercised
in milliseconds with an injected clock.  Runner/engine tests use the same
tiny pure-XLA model as test_serving.py; the headline pins are the ISSUE
acceptance properties: (a) the sessionless path and session COLD frames
are bitwise-equal to the pre-session build (same program for the former,
same math for the latter), (b) a zero warm init reproduces the cold
output bitwise (``disp = 0 + flow_init``), (c) session frames chain
in order and never share a dispatch with another family, (d) dead
sessions fail with the typed ``SessionExpired`` → HTTP 410, and (e) the
warm executable families join prewarm and the /readyz target and get
distinct persistent-cache keys.
"""

import io
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_stereo_tpu.serving.sessions import (SessionExpired,
                                              SessionsDisabled,
                                              SessionStore, frame_delta,
                                              frame_thumbnail)

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")
ITERS = 1


# ------------------------------------------------------------ session store
class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_store_ttl_expiry_typed_and_tombstone_ages_out():
    clock = FakeClock()
    store = SessionStore(capacity=4, ttl_s=10.0, clock=clock)
    sess, created = store.get_or_create("a")
    assert created and store.active_count == 1
    clock.t += 5.0
    _, created = store.get_or_create("a")      # touch keeps it alive
    assert not created
    clock.t += 10.1                            # past TTL since last touch
    with pytest.raises(SessionExpired) as e:
        store.get_or_create("a")
    assert e.value.reason == "expired" and e.value.session_id == "a"
    assert store.active_count == 0
    # SessionExpired is a KeyError subclass (store.get contract)
    with pytest.raises(KeyError):
        store.get("a")
    # the tombstone itself ages out after another TTL: the id is fresh
    clock.t += 10.1
    _, created = store.get_or_create("a")
    assert created


def test_store_lru_eviction_at_capacity():
    clock = FakeClock()
    store = SessionStore(capacity=2, ttl_s=100.0, clock=clock)
    store.get_or_create("a")
    clock.t += 1
    store.get_or_create("b")
    clock.t += 1
    store.get_or_create("a")                   # refresh: b is now LRU
    clock.t += 1
    store.get_or_create("c")                   # evicts b
    assert store.active_count == 2
    with pytest.raises(SessionExpired) as e:
        store.get_or_create("b")
    assert e.value.reason == "evicted"
    store.get_or_create("a")                   # survivors unaffected
    store.get_or_create("c")


def test_store_close_returns_stats_and_tombstones():
    store = SessionStore(capacity=4, ttl_s=100.0, clock=FakeClock())
    sess, _ = store.get_or_create("cam")
    sess.note_result(flow_low=np.zeros((4, 4), np.float32),
                     thumb=None, bucket=(32, 32), raw_shape=(30, 30),
                     warm=False, iters_used=3)
    sess.note_result(flow_low=np.zeros((4, 4), np.float32),
                     thumb=None, bucket=(32, 32), raw_shape=(30, 30),
                     warm=True, iters_used=1)
    stats = store.close("cam")
    assert stats["frames"] == 2 and stats["warm_frames"] == 1
    assert stats["iters_used_mean"] == 2.0
    with pytest.raises(SessionExpired) as e:
        store.get_or_create("cam")
    assert e.value.reason == "closed"
    with pytest.raises(KeyError):
        store.close("never-existed")


def test_store_inflight_session_immune_to_sweep():
    """A frame in flight (ordering lock held) longer than the TTL must
    not expire its session mid-dispatch — the completion callback
    touches it back to freshness (the first-frame-compile case)."""
    clock = FakeClock()
    store = SessionStore(capacity=4, ttl_s=1.0, clock=clock)
    sess, _ = store.get_or_create("slow")
    assert sess.order_lock.acquire(timeout=1)
    clock.t += 100.0                           # way past TTL, but in flight
    assert store.active_count == 1             # sweep skipped it
    store.touch("slow")
    sess.order_lock.release()
    clock.t += 0.5
    _, created = store.get_or_create("slow")
    assert not created                         # still the same session


def test_store_concurrent_access_two_clients():
    """The satellite's concurrent two-client pin at the store level: two
    threads hammering their own ids (plus overlap on a shared one) never
    corrupt the table or double-create."""
    store = SessionStore(capacity=64, ttl_s=100.0)
    created_counts = {"x": 0, "y": 0, "shared": 0}
    lock = threading.Lock()
    errors = []

    def client(own: str):
        try:
            for _ in range(200):
                for sid in (own, "shared"):
                    _, created = store.get_or_create(sid)
                    if created:
                        with lock:
                            created_counts[sid] += 1
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=client, args=(own,))
               for own in ("x", "y")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert created_counts == {"x": 1, "y": 1, "shared": 1}
    assert store.active_count == 3


def test_frame_thumbnail_and_delta():
    img = np.full((64, 96, 3), 100, np.uint8)
    thumb = frame_thumbnail(img)
    assert thumb.shape == (4, 6)
    assert np.allclose(thumb, 100.0)
    bright = frame_thumbnail(np.full((64, 96, 3), 228, np.uint8))
    assert frame_delta(thumb, thumb) == 0.0
    assert frame_delta(thumb, bright) == pytest.approx(128.0)
    assert frame_delta(None, thumb) is None
    assert frame_delta(thumb, frame_thumbnail(
        np.zeros((32, 32, 3), np.uint8))) is None   # shape change


# ------------------------------------------------------------------ runner
@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


def _pair(hw=(48, 64), seed=3):
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 255, hw + (3,), dtype=np.uint8)
    return left, np.roll(left, -3, axis=1)


def test_run_stream_cold_bitwise_parity_with_sessionless(tiny_model):
    """The acceptance pin: a cold stream frame (no previous state) runs
    the same math as the sessionless path — the extra flow_low output
    changes nothing about flow_up, bitwise."""
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    runner = InferenceRunner(cfg, variables, iters=ITERS)
    left, right = _pair()
    flow, _ = runner(left, right)
    frame = runner.run_stream(left, right)
    assert not frame.warm and frame.iters_used is None
    assert np.array_equal(frame.flow, flow), \
        "cold stream frame must be bitwise-equal to the sessionless path"
    f = cfg.downsample_factor
    assert frame.flow_low.shape == (64 // f, 64 // f)  # padded low-res
    assert frame.flow_low.dtype == np.float32


def test_run_stream_zero_init_bitwise_equals_cold(tiny_model):
    """disp = 0 + flow_init: a zero warm init must reproduce the cold
    output bitwise — the warm program differs only by its seeding."""
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    runner = InferenceRunner(cfg, variables, iters=ITERS)
    left, right = _pair(seed=5)
    cold = runner.run_stream(left, right)
    warm = runner.run_stream(left, right,
                             prev_flow_low=np.zeros_like(cold.flow_low))
    assert warm.warm
    assert np.array_equal(warm.flow, cold.flow)
    assert np.array_equal(warm.flow_low, cold.flow_low)


def test_run_stream_state_mismatch_raises(tiny_model):
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    runner = InferenceRunner(cfg, variables, iters=ITERS)
    left, right = _pair()
    with pytest.raises(ValueError, match="low-res grid"):
        runner.run_stream(left, right,
                          prev_flow_low=np.zeros((3, 3), np.float32))


def test_run_stream_early_exit_reports_iters(tiny_model):
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    runner = InferenceRunner(cfg, variables, iters=3,
                             exit_threshold_px=1e-6, exit_min_iters=1)
    left, right = _pair()
    cold = runner.run_stream(left, right)
    assert cold.iters_used is not None and 1 <= cold.iters_used <= 3
    warm = runner.run_stream(left, right, prev_flow_low=cold.flow_low)
    assert warm.iters_used is not None and runner.iters_used_mean() > 0


# ------------------------------------------------------------------ engine
def _structured(hw=(48, 64), level=40):
    """A smooth structured frame (NOT noise: the scene-cut thumbnails
    mean-pool, so only structured content moves the delta)."""
    h, w = hw
    ramp = np.linspace(0, 120, w, dtype=np.float32)[None, :] + level
    img = np.broadcast_to(ramp, (h, w)).astype(np.uint8)
    return np.stack([img] * 3, axis=-1)


def test_engine_session_lifecycle_and_parity(tiny_model):
    """Frame 0 cold + bitwise-equal to both the stateless engine path
    and the solo runner; frame 1 warm with state chained; close returns
    stats; a closed id 410s (SessionExpired)."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    left, right = _pair()
    solo_flow, _ = solo(left, right)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, iters=ITERS,
                                   sessions=True)) as svc:
        stateless = svc.infer(left, right, timeout=300)
        assert stateless.session_id is None and not stateless.warm
        assert np.array_equal(stateless.flow, solo_flow)

        f0 = svc.infer_session("cam", left, right, timeout=300)
        assert (f0.session_id, f0.frame_index, f0.warm) == ("cam", 0,
                                                            False)
        assert np.array_equal(f0.flow, solo_flow), \
            "session cold frame must be bitwise-equal to sessionless"
        assert f0.flow_low is not None and f0.flow_low.dtype == np.float32

        f1 = svc.infer_session("cam", left, right, timeout=300)
        assert f1.warm and f1.frame_index == 1 and not f1.scene_cut
        assert f1.frame_delta == pytest.approx(0.0)

        assert svc.metrics.session_frames("cold") == 1
        assert svc.metrics.session_frames("warm") == 1
        text = svc.metrics.render_text()
        assert "serve_sessions_active 1" in text
        assert 'serve_session_frames_total{mode="warm"} 1' in text

        stats = svc.close_session("cam")
        assert stats["frames"] == 2 and stats["warm_frames"] == 1
        with pytest.raises(SessionExpired) as e:
            svc.infer_session("cam", left, right, timeout=300)
        assert e.value.reason == "closed"


def test_engine_scene_cut_falls_back_cold(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=1, batch_sizes=(1,),
                                   iters=ITERS, sessions=True,
                                   scene_cut_threshold=40.0)) as svc:
        a = _structured(level=20)
        b = _structured(level=24)              # small drift: stays warm
        c = 255 - _structured(level=20)        # inversion: hard cut
        svc.infer_session("s", a, a.copy(), timeout=300)
        f1 = svc.infer_session("s", b, b.copy(), timeout=300)
        assert f1.warm and not f1.scene_cut
        f2 = svc.infer_session("s", c, c.copy(), timeout=300)
        assert not f2.warm and f2.scene_cut
        assert f2.frame_delta is not None and f2.frame_delta > 40.0
        assert svc.metrics.scene_cuts.value == 1
        # the stream recovers: the cut frame re-seeded the state
        f3 = svc.infer_session("s", c, c.copy(), timeout=300)
        assert f3.warm and not f3.scene_cut
        # delta histogram observed every warm-candidate frame
        assert svc.metrics.frame_delta.count == 3


def test_engine_session_frames_strictly_ordered(tiny_model):
    """The dispatch-cycle ordering pin: frame N+1 of a session cannot
    even ENTER the queue until frame N resolved — submitted concurrently
    under a paused queue, frames complete in submission order and frame
    N+1 warm-starts from frame N."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, iters=ITERS,
                                   sessions=True)) as svc:
        svc.infer_session("s", left, right, timeout=300)  # compile + seed
        svc.queue.pause()
        done = []
        futs = {}

        def frame(idx):
            fut = svc.submit_session("s", left, right)
            futs[idx] = fut
            fut.add_done_callback(lambda f: done.append(idx))

        t1 = threading.Thread(target=frame, args=(1,))
        t1.start()
        time.sleep(0.2)
        t2 = threading.Thread(target=frame, args=(2,))
        t2.start()
        time.sleep(0.2)
        # frame 2 is blocked on the session's ordering lock — not queued
        assert svc.queue.depth == 1
        svc.queue.resume()
        t1.join(timeout=60)
        t2.join(timeout=60)
        r1 = futs[1].result(timeout=300)
        r2 = futs[2].result(timeout=300)
        assert done == [1, 2]
        assert (r1.frame_index, r2.frame_index) == (1, 2)
        assert r1.warm and r2.warm


def test_engine_two_sessions_stream_concurrently(tiny_model):
    """Concurrent two-client access: two sessions interleave frames
    freely (only same-session frames serialize); both streams end fully
    warm after frame 0 with their own state."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, iters=ITERS,
                                   sessions=True)) as svc:
        n_frames, results = 4, {}

        def client(sid, seed):
            left, right = _pair(seed=seed)
            results[sid] = [svc.infer_session(sid, left, right,
                                              timeout=300)
                            for _ in range(n_frames)]

        threads = [threading.Thread(target=client, args=(f"c{i}", i))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for sid in ("c0", "c1"):
            rs = results[sid]
            assert [r.frame_index for r in rs] == list(range(n_frames))
            assert [r.warm for r in rs] == [False] + [True] * (n_frames - 1)
        assert svc.sessions.active_count == 2
        assert svc.metrics.session_frames("warm") == 2 * (n_frames - 1)


def test_engine_keyframe_guard_reseeds_on_cap(tiny_model):
    """A warm frame on an early-exit tier that runs to the iteration cap
    never converged: its state is dropped (serve_session_reseeds_total)
    and the NEXT frame cold-starts — warm-chain drift is bounded by one
    segment.  A threshold far below any real update (1e-9 px) pins
    every frame at the cap deterministically."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=2, sessions=True,
            tiers=("never:0.000000001:1", "quality"),
            default_tier="never")) as svc:
        results = [svc.infer_session("s", left, right, timeout=300)
                   for _ in range(4)]
        assert [r.iters_used for r in results] == [2] * 4  # all at cap
        # frame 0 cold; frame 1 warm (cold state trusted) but hits the
        # cap -> reseed; frame 2 cold again; frame 3 warm; ...
        assert [r.warm for r in results] == [False, True, False, True]
        assert svc.metrics.session_reseeds.value == 2
        assert "serve_session_reseeds_total 2" in \
            svc.metrics.render_text()


def test_engine_session_ttl_expiry_typed(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=1, batch_sizes=(1,),
                                   iters=ITERS, sessions=True,
                                   session_ttl_s=100.0)) as svc:
        svc.infer_session("s", left, right, timeout=300)
        # expire deterministically: rewind the session's last-used stamp
        svc.sessions.get("s").last_used_mono -= 101.0
        with pytest.raises(SessionExpired) as e:
            svc.infer_session("s", left, right, timeout=300)
        assert e.value.reason == "expired"
        assert svc.metrics.sessions_expired.value == 1


def test_engine_sessions_disabled_typed(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=1, batch_sizes=(1,),
                                   iters=ITERS)) as svc:
        assert svc.sessions is None
        with pytest.raises(SessionsDisabled):
            svc.infer_session("s", left, right, timeout=300)
        with pytest.raises(SessionsDisabled):
            svc.close_session("s")


def test_engine_warm_families_join_prewarm_and_ready(tiny_model):
    """Warm/state executable families are first-class warm surface: the
    /readyz target includes them, prewarm compiles them, and their
    persistent-cache keys never collide with the base program's (the
    satellite fix: key includes the flow_init arity)."""
    from raft_stereo_tpu.serving import (FAMILY_BASE, FAMILY_STATE,
                                         FAMILY_WARM, ServeConfig,
                                         StereoService)

    cfg, variables = tiny_model
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=ITERS, sessions=True,
            warmup_shapes=((48, 64),), prewarm_on_init=False)) as svc:
        families = {t[4] for t in svc._warm_target}
        assert families == {FAMILY_BASE, FAMILY_STATE, FAMILY_WARM}
        assert not svc.ready
        svc.prewarm((48, 64))
        assert svc.ready
        # distinct disk-cache keys per family (warm/cold arity)
        keys = {svc._disk_key((64, 64), 1, 0, None, fam)
                for fam in (FAMILY_BASE, FAMILY_STATE, FAMILY_WARM)}
        assert len(keys) == 3
        # r24: confidence is one more key coordinate — every family's
        # persist key moves when it is on, and none mention it when off.
        with StereoService(cfg, variables, ServeConfig(
                max_batch=1, batch_sizes=(1,), iters=ITERS,
                sessions=True, warmup_shapes=((48, 64),),
                prewarm_on_init=False, confidence=True)) as conf_svc:
            conf_keys = {conf_svc._disk_key((64, 64), 1, 0, None, fam)
                         for fam in (FAMILY_BASE, FAMILY_STATE,
                                     FAMILY_WARM)}
            assert len(conf_keys) == 3 and not (conf_keys & keys)
        # prewarmed programs serve immediately (no first-request compile
        # for any family): a session's first two frames exercise state +
        # warm
        left, right = _pair()
        f0 = svc.infer_session("s", left, right, timeout=300)
        f1 = svc.infer_session("s", left, right, timeout=300)
        assert not f0.warm and f1.warm


def test_stateless_engine_warm_surface_unchanged(tiny_model):
    """sessions=False keeps the round-13 warm surface: base family only
    — no extra compiles, no extra readiness entries."""
    from raft_stereo_tpu.serving import FAMILY_BASE, ServeConfig, \
        StereoService

    cfg, variables = tiny_model
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=ITERS,
            warmup_shapes=((48, 64),), prewarm_on_init=False)) as svc:
        assert {t[4] for t in svc._warm_target} == {FAMILY_BASE}
        assert len(svc._warm_target) == 1


# -------------------------------------------------------------------- http
@pytest.fixture()
def http_server(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=2, iters=ITERS,
                                    sessions=True, session_ttl_s=100.0))
    server = StereoHTTPServer(svc, port=0).start()
    yield server, svc
    server.shutdown()
    svc.close()


def _post_stream(url, sid, left, right, path=None):
    buf = io.BytesIO()
    np.savez(buf, left=left, right=right)
    req = urllib.request.Request(
        path or f"{url}/v1/stream/{sid}", data=buf.getvalue(),
        method="POST", headers={"Content-Type": "application/x-npz"})
    return urllib.request.urlopen(req, timeout=300)


def test_http_stream_protocol(http_server):
    """The wire contract: session headers on frames, 410 on dead ids,
    DELETE stats, 400 on a missing id, sessions_active on /healthz."""
    server, svc = http_server
    url = server.url
    left, right = _pair()

    with _post_stream(url, "cam1", left, right) as resp:
        assert resp.status == 200
        assert resp.headers["X-Session-Id"] == "cam1"
        assert resp.headers["X-Warm"] == "0"
        assert resp.headers["X-Frame-Index"] == "0"
        disp = np.load(io.BytesIO(resp.read()))
        assert disp.shape == left.shape[:2]
    with _post_stream(url, "cam1", left, right) as resp:
        assert resp.headers["X-Warm"] == "1"
        assert resp.headers["X-Frame-Index"] == "1"
        assert float(resp.headers["X-Frame-Delta"]) == pytest.approx(0.0)

    # X-Session-Id header addressing on the bare path joins the session
    buf = io.BytesIO()
    np.savez(buf, left=left, right=right)
    req = urllib.request.Request(
        f"{url}/v1/stream", data=buf.getvalue(), method="POST",
        headers={"Content-Type": "application/x-npz",
                 "X-Session-Id": "cam1"})
    with urllib.request.urlopen(req, timeout=300) as resp:
        assert resp.headers["X-Session-Id"] == "cam1"
        assert resp.headers["X-Frame-Index"] == "2"
        assert resp.headers["X-Warm"] == "1"


def test_http_stream_errors_typed(http_server):
    server, svc = http_server
    url = server.url
    left, right = _pair()

    # missing session id -> 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post_stream(url, None, left, right, path=f"{url}/v1/stream")
    assert e.value.code == 400

    # expired session -> typed 410
    with _post_stream(url, "gone", left, right):
        pass
    svc.sessions.get("gone").last_used_mono -= 101.0
    with pytest.raises(urllib.error.HTTPError) as e:
        _post_stream(url, "gone", left, right)
    assert e.value.code == 410
    body = json.loads(e.value.read())
    assert body["error"] == "session_expired"
    assert body["reason"] == "expired"

    # DELETE: stats, then 410; unknown id -> 404
    with _post_stream(url, "cam2", left, right):
        pass
    req = urllib.request.Request(f"{url}/v1/stream/cam2", method="DELETE")
    with urllib.request.urlopen(req, timeout=60) as resp:
        stats = json.loads(resp.read())
    assert stats["status"] == "closed" and stats["frames"] == 1
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(
            urllib.request.Request(f"{url}/v1/stream/cam2",
                                   method="DELETE"), timeout=60)
    assert e.value.code == 410
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(
            urllib.request.Request(f"{url}/v1/stream/nope",
                                   method="DELETE"), timeout=60)
    assert e.value.code == 404

    # healthz reports live sessions ("gone" expired, "cam2" closed -> 0)
    with urllib.request.urlopen(f"{url}/healthz", timeout=60) as resp:
        health = json.loads(resp.read())
    assert health["sessions_active"] == 0


def test_http_sessions_disabled_400(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=1, batch_sizes=(1,),
                                    iters=ITERS))
    server = StereoHTTPServer(svc, port=0).start()
    try:
        left, right = _pair()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_stream(server.url, "cam", left, right)
        assert e.value.code == 400
        assert json.loads(e.value.read())["error"] == "sessions_disabled"
    finally:
        server.shutdown()
        svc.close()


# --------------------------------------------- crashed session dispatches
# Round-16 regression (r13 requeue x r14 submit_session cross): a chaos-
# crashed dispatch carrying a SESSION frame must release the per-session
# ordering lock through its future and invalidate warm state, so the
# requeued frame cold-starts instead of chaining off a flow the crashed
# dispatch never produced.

def test_crashed_warm_frame_cold_retries_and_stream_survives(tiny_model):
    """A warm frame whose dispatch crashes is demoted to a COLD start
    for its retry (a crash caused by the warm init would otherwise burn
    every attempt deterministically), the session's stored state is
    dropped, and the stream keeps flowing — the ordering lock is
    released by the retry's success, never leaked."""
    from raft_stereo_tpu.serving import (ChaosConfig, ChaosInjector,
                                         ServeConfig, StereoService)

    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=1, batch_sizes=(1,),
                                   iters=ITERS, sessions=True,
                                   max_dispatch_attempts=3,
                                   retry_backoff_ms=1.0)) as svc:
        f0 = svc.infer_session("s", left, right, timeout=300)
        assert not f0.warm                       # cold seed, clean
        # Arm chaos only now (the dispatch path re-reads the attribute):
        # the NEXT dispatch — frame 1, warm — crashes exactly once.
        svc.chaos = ChaosInjector(
            ChaosConfig(seed=1, crash_rate=1.0, max_faults=1),
            observe=svc.metrics.observe_injected_fault)
        f1 = svc.infer_session("s", left, right, timeout=300)
        assert f1.attempts == 2, "the crash must have been retried"
        assert not f1.warm, \
            "the requeued frame must COLD-start: its warm init was " \
            "voided by the crash"
        assert svc.metrics.retries.value == 1
        # lock released + state re-seeded by the cold retry: the next
        # frame warm-starts off the RETRY's output.
        f2 = svc.infer_session("s", left, right, timeout=300)
        assert f2.warm and f2.attempts == 1
        assert svc.sessions.get("s").cold_frames == 2


def test_poisoned_session_frame_releases_lock_and_next_frame_cold(
        tiny_model):
    """A session frame poisoned (crashed on every attempt) must release
    the ordering lock via its typed failure AND leave the session in a
    cold-start state: the next frame must not warm-chain across the gap
    off a flow the poisoned dispatch never produced."""
    from raft_stereo_tpu.serving import (ChaosConfig, ChaosInjector,
                                         RequestPoisoned, ServeConfig,
                                         StereoService)

    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=1, batch_sizes=(1,),
                                   iters=ITERS, sessions=True,
                                   max_dispatch_attempts=1)) as svc:
        f0 = svc.infer_session("s", left, right, timeout=300)
        assert not f0.warm
        assert svc.sessions.get("s").flow_low is not None
        svc.chaos = ChaosInjector(
            ChaosConfig(seed=2, crash_rate=1.0, max_faults=1),
            observe=svc.metrics.observe_injected_fault)
        with pytest.raises(RequestPoisoned):
            svc.infer_session("s", left, right, timeout=300)
        assert svc.metrics.poisoned.value == 1
        # the warm state died with the crashed dispatch
        assert svc.sessions.get("s").flow_low is None
        # lock released by the typed failure: the stream continues, COLD
        f2 = svc.infer_session("s", left, right, timeout=300)
        assert not f2.warm, \
            "the frame after a poisoned one must cold-start (no " \
            "chaining across the gap)"
        f3 = svc.infer_session("s", left, right, timeout=300)
        assert f3.warm                           # chain re-established


# ------------------------------------------------- session handoff (round 18)
def _filled_store(n=6, with_ctx=True, with_hidden=True):
    from raft_stereo_tpu.serving.sessions import SessionStore

    store = SessionStore()
    rng = np.random.default_rng(7)
    for i in range(n):
        sess, _ = store.get_or_create(f"cam-{i}")
        sess.note_result(
            flow_low=rng.standard_normal((8, 12)).astype(np.float32),
            thumb=rng.standard_normal((3, 4)).astype(np.float32),
            bucket=(32, 48), raw_shape=(30, 45),
            warm=(i % 2 == 0), iters_used=3 + i,
            # the round-19 h-tree rides the v2 codec (three levels,
            # shrinking like the real per-level GRU states)
            hidden=(tuple(rng.standard_normal((8 >> l, 12 >> l, 4)
                                              ).astype(np.float32)
                          for l in range(3))
                    if with_hidden and i % 3 != 2 else None))
        if with_ctx and i % 2 == 0:
            sess.ctx = (rng.standard_normal((2, 2)).astype(np.float32),
                        (rng.standard_normal((4,)).astype(np.float32),
                         None))
    return store


def test_handoff_export_import_round_trip():
    """Round-trip property: every field that decides the next frame's
    warmth — flow, thumbnail, bucket, raw shape, counters, ctx —
    survives export()/import_() exactly."""
    from raft_stereo_tpu.serving.sessions import SessionStore

    src = _filled_store()
    blob = src.export()
    dst = SessionStore()
    imported, skipped = dst.import_(blob)
    assert (imported, skipped) == (6, 0)
    for i in range(6):
        a = src.get(f"cam-{i}")
        b = dst.get(f"cam-{i}")
        assert np.array_equal(a.flow_low, b.flow_low)
        assert np.array_equal(a.thumb, b.thumb)
        assert a.bucket == b.bucket and a.raw_shape == b.raw_shape
        for field in ("frame_index", "warm_frames", "cold_frames",
                      "scene_cuts", "iters_used_sum",
                      "iters_used_frames"):
            assert getattr(a, field) == getattr(b, field), field
        if a.ctx is None:
            assert b.ctx is None
        else:
            assert np.array_equal(a.ctx[0], b.ctx[0])
            assert np.array_equal(a.ctx[1][0], b.ctx[1][0])
            assert b.ctx[1][1] is None
        if a.hidden is None:
            assert b.hidden is None
        else:
            assert len(b.hidden) == len(a.hidden)
            for ha, hb in zip(a.hidden, b.hidden):
                assert np.array_equal(ha, hb)


def test_handoff_corrupt_entry_degrades_to_cold_never_crashes():
    """Satellite property sweep: flip any byte of the blob — the
    importer never raises, and at worst the touched session is skipped
    (cold start) while the rest import intact."""
    from raft_stereo_tpu.serving.sessions import (SessionStore,
                                                  parse_handoff_blob)

    src = _filled_store(n=4, with_ctx=False)
    blob = src.export()
    rng = np.random.default_rng(11)
    for _ in range(40):
        bad = bytearray(blob)
        pos = int(rng.integers(0, len(bad)))
        bad[pos] ^= 0xFF
        records, skipped = parse_handoff_blob(bytes(bad))  # never raises
        assert len(records) + skipped <= 4
        dst = SessionStore()
        imported, _ = dst.import_(bytes(bad))
        assert imported == len(records)
    # truncation at every decile: never a crash
    for frac in range(0, 10):
        cut = blob[: len(blob) * frac // 10]
        records, _ = parse_handoff_blob(bytes(cut))
        assert isinstance(records, dict)


def test_handoff_import_respects_live_and_tombstoned_ids():
    from raft_stereo_tpu.serving.sessions import SessionStore

    src = _filled_store(n=3, with_ctx=False)
    blob = src.export()
    dst = SessionStore()
    live, _ = dst.get_or_create("cam-0")        # live id: import skips
    live.frame_index = 99
    dst.close(dst.get_or_create("cam-1")[0].session_id)   # tombstoned
    imported, skipped = dst.import_(blob)
    assert imported == 1 and skipped == 2
    assert dst.get("cam-0").frame_index == 99, \
        "an import must never clobber a live stream"
    with pytest.raises(SessionExpired):
        dst.get("cam-1")


def test_engine_handoff_state_numerically_identical(tiny_model):
    """ISSUE acceptance: a handed-off session's next warm dispatch is
    numerically identical to the dispatch a never-drained engine would
    have produced — the handoff moves state, it does not perturb it."""
    import tempfile

    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    with tempfile.TemporaryDirectory() as store_dir:
        serve_cfg = ServeConfig(max_batch=1, batch_sizes=(1,), iters=2,
                                sessions=True,
                                executable_cache_dir=store_dir)
        ref = StereoService(cfg, variables, serve_cfg)   # never drained
        a = StereoService(cfg, variables, serve_cfg)     # drains
        b = StereoService(cfg, variables, serve_cfg)     # inherits
        try:
            for svc in (ref, a):
                f1 = svc.infer_session("cam", left, right, timeout=300)
                f2 = svc.infer_session("cam", left, right, timeout=300)
                assert not f1.warm and f2.warm
            a.begin_shutdown()
            manifest = a.publish_handoff()
            assert manifest["count"] == 1 and manifest["artifact"]
            f3_ref = ref.infer_session("cam", left, right, timeout=300)
            f3_b = b.infer_session("cam", left, right, timeout=300,
                                   handoff_key=manifest["artifact"])
            assert f3_b.warm, \
                "the first post-handoff frame must dispatch WARM"
            assert f3_b.frame_index == 2
            assert np.array_equal(f3_b.flow, f3_ref.flow), \
                "handoff-imported state must be numerically identical"
            assert b.metrics.sessions_adopted.value == 1
            assert a.metrics.sessions_exported.value == 1
            # chain continues warm on the inheritor
            f4 = b.infer_session("cam", left, right, timeout=300)
            assert f4.warm and f4.frame_index == 3
            # a MISSING artifact key degrades to a plain cold start
            miss = b.infer_session("other", left, right, timeout=300,
                                   handoff_key="deadbeef" * 8)
            assert not miss.warm and miss.frame_index == 0, \
                "a missing handoff artifact degrades to a cold start"
        finally:
            ref.close()
            a.close()
            b.close()


@pytest.mark.slow
def test_http_stream_handoff_header(tiny_model):
    """The HTTP leg of the handoff: GET /admin/handoff serves the
    manifest after a drain published it, and X-Handoff-Artifact on the
    inheriting replica's first frame imports the state (X-Warm: 1).
    Slow tier: the engine-level numeric-identity test above pins the
    same import path; this adds only the header plumbing, which the
    fleet smoke also exercises end-to-end on every CI run."""
    import json as json_mod
    import tempfile
    import urllib.request

    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    left, right = _pair()
    with tempfile.TemporaryDirectory() as store_dir:
        serve_cfg = ServeConfig(max_batch=1, batch_sizes=(1,), iters=2,
                                sessions=True,
                                executable_cache_dir=store_dir)
        a = StereoService(cfg, variables, serve_cfg)
        b = StereoService(cfg, variables, serve_cfg)
        sa = StereoHTTPServer(a, port=0).start()
        sb = StereoHTTPServer(b, port=0).start()
        try:
            # no manifest yet -> typed 404
            try:
                urllib.request.urlopen(f"{sa.url}/admin/handoff",
                                       timeout=10)
                raise AssertionError("expected 404 before publish")
            except urllib.error.HTTPError as e:
                assert e.code == 404
            _post_stream(sa.url, "cam", left, right).read()
            with _post_stream(sa.url, "cam", left, right) as resp:
                assert resp.headers["X-Warm"] == "1"
            a.begin_shutdown()
            a.publish_handoff()
            with urllib.request.urlopen(f"{sa.url}/admin/handoff",
                                        timeout=10) as resp:
                manifest = json_mod.load(resp)
            assert manifest["sessions"] == ["cam"]
            buf = io.BytesIO()
            np.savez(buf, left=left, right=right)
            req = urllib.request.Request(
                f"{sb.url}/v1/stream/cam", data=buf.getvalue(),
                method="POST",
                headers={"Content-Type": "application/x-npz",
                         "X-Handoff-Artifact": manifest["artifact"]})
            with urllib.request.urlopen(req, timeout=300) as resp:
                assert resp.headers["X-Warm"] == "1", \
                    "the inherited frame must dispatch warm over HTTP"
                assert resp.headers["X-Frame-Index"] == "2"
        finally:
            sa.shutdown()
            sb.shutdown()
            a.close()
            b.close()


# --------------------------------------- hidden-state warm start (round 19)
def test_run_stream_hidden_tree_structure_and_chain(tiny_model):
    """carry_hidden returns one evolved state per GRU level at the
    level's own geometry; feeding it back runs the warm-h program; a
    hidden tree without its disparity half is a typed error."""
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    runner = InferenceRunner(cfg, variables, iters=ITERS)
    left, right = _pair()
    cold = runner.run_stream(left, right, carry_hidden=True)
    assert cold.hidden is not None and len(cold.hidden) == cfg.n_gru_layers
    f = cfg.downsample_factor
    for l, h in enumerate(cold.hidden):
        assert h.shape == (64 // (f * 2 ** l), 64 // (f * 2 ** l),
                           cfg.hidden_dims[l])
    warm = runner.run_stream(left, right, prev_flow_low=cold.flow_low,
                             prev_hidden=cold.hidden)
    assert warm.warm and warm.hidden is not None
    with pytest.raises(ValueError, match="prev_hidden needs"):
        runner.run_stream(left, right, prev_hidden=cold.hidden)
    # the hidden-off program surface is untouched: a plain stream frame
    # still returns no hidden and stays bitwise-pinned upstream
    plain = runner.run_stream(left, right)
    assert plain.hidden is None


def test_engine_session_hidden_lifecycle_and_families(tiny_model):
    """session_hidden=True swaps the session families for their _h
    variants (prewarm/readyz surface + distinct persist keys), frame 0
    returns the hidden tree, frame 1 consumes it (warm_hidden), and the
    state invalidates in lockstep with the flow on a scene cut."""
    from raft_stereo_tpu.serving import (FAMILY_BASE, FAMILY_STATE_H,
                                         FAMILY_WARM_H, ServeConfig,
                                         StereoService)

    cfg, variables = tiny_model
    with StereoService(cfg, variables, ServeConfig(
            max_batch=1, batch_sizes=(1,), iters=ITERS, sessions=True,
            session_hidden=True, scene_cut_threshold=40.0,
            warmup_shapes=((48, 64),), prewarm_on_init=False)) as svc:
        families = {t[4] for t in svc._warm_target}
        assert families == {FAMILY_BASE, FAMILY_STATE_H, FAMILY_WARM_H}
        keys = {svc._disk_key((64, 64), 1, 0, None, fam)
                for fam in (FAMILY_BASE, FAMILY_STATE_H, FAMILY_WARM_H,
                            "state", "warm")}
        assert len(keys) == 5, \
            "h-family persist keys must not collide with the r14 ones"
        a = _structured(level=40)
        f0 = svc.infer_session("s", a, a, timeout=300)
        assert not f0.warm and not f0.warm_hidden
        assert f0.hidden is not None and len(f0.hidden) == cfg.n_gru_layers
        sess = svc.sessions.get("s")
        assert sess.hidden is not None
        f1 = svc.infer_session("s", a, a, timeout=300)
        assert f1.warm and f1.warm_hidden
        # hard scene cut: cold fallback AND the h-tree re-seeds from the
        # cut frame (lockstep with the flow state)
        b = 255 - _structured(level=20)
        f2 = svc.infer_session("s", b, b, timeout=300)
        assert f2.scene_cut and not f2.warm and not f2.warm_hidden
        assert svc.sessions.get("s").hidden is not None  # re-seeded
        f3 = svc.infer_session("s", b, b, timeout=300)
        assert f3.warm and f3.warm_hidden


def test_session_note_result_drops_hidden_with_flow():
    """The lockstep rule at the store level: a keyframe-guard reseed
    (flow_low=None) must drop the hidden tree too — a kept trajectory
    with a dropped disparity would be exactly the torn warm-h input the
    engine must never build."""
    from raft_stereo_tpu.serving.sessions import SessionStore

    store = SessionStore()
    sess, _ = store.get_or_create("s")
    h = (np.ones((4, 6, 2), np.float32),)
    sess.note_result(flow_low=np.zeros((4, 6), np.float32), thumb=None,
                     bucket=(32, 48), raw_shape=(32, 48), warm=False,
                     iters_used=None, hidden=h)
    assert sess.hidden is h
    sess.note_result(flow_low=None, thumb=None, bucket=(32, 48),
                     raw_shape=(32, 48), warm=True, iters_used=None,
                     hidden=h)
    assert sess.flow_low is None and sess.hidden is None


def test_handoff_fingerprint_mismatch_refused_typed():
    """The r18 follow-up: a blob stamped with another exec-config
    fingerprint is refused wholesale at import — every session counts
    skipped, none installs."""
    from raft_stereo_tpu.serving.sessions import (SessionStore,
                                                  handoff_fingerprint)

    src = _filled_store(n=3)
    blob = src.export(config_fingerprint="aa" * 32)
    assert handoff_fingerprint(blob) == "aa" * 32
    dst = SessionStore()
    imported, skipped = dst.import_(blob, expect_fingerprint="bb" * 32)
    assert (imported, skipped) == (0, 3)
    assert dst.active_count == 0
    # matching fingerprint imports normally
    imported, skipped = dst.import_(blob, expect_fingerprint="aa" * 32)
    assert imported == 3
    # an UNSTAMPED blob (fingerprint None) is not refused — there is
    # nothing to compare; per-entry checksums still guard the payload
    blob2 = _filled_store(n=2).export()
    dst2 = SessionStore()
    assert dst2.import_(blob2, expect_fingerprint="bb" * 32)[0] == 2


@pytest.mark.slow
def test_engine_handoff_config_mismatch_cold_starts_typed(tiny_model):
    """Engine-level config-fingerprint gate: an inheritor compiled at a
    different depth cap refuses the artifact with the typed
    serve_handoff_import_skipped_total{reason="config_mismatch"} and
    the frame cold-starts (never a wrong-geometry warm dispatch).
    Slow tier for the tier-1 wall budget: the fingerprint REFUSAL
    contract itself is pinned in tier-1 by the store-level
    test_handoff_fingerprint_mismatch_refused_typed (no JAX); this adds
    the engine wiring (two compiled engines), which the metric check in
    the engine smoke also exercises."""
    import tempfile

    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    with tempfile.TemporaryDirectory() as store_dir:
        a_cfg = ServeConfig(max_batch=1, batch_sizes=(1,), iters=1,
                            sessions=True, session_hidden=True,
                            executable_cache_dir=store_dir)
        b_cfg = ServeConfig(max_batch=1, batch_sizes=(1,), iters=2,
                            sessions=True, session_hidden=True,
                            executable_cache_dir=store_dir)
        with StereoService(cfg, variables, a_cfg) as a:
            a.infer_session("cam", left, right, timeout=300)
            a.begin_shutdown()
            manifest = a.publish_handoff()
            assert manifest["config_fingerprint"] == \
                a.exec_config_fingerprint()
        with StereoService(cfg, variables, b_cfg) as b:
            assert b.exec_config_fingerprint() != \
                manifest["config_fingerprint"]
            fb = b.infer_session("cam", left, right, timeout=300,
                                 handoff_key=manifest["artifact"])
            assert not fb.warm and fb.frame_index == 0
            assert b.metrics.handoff_skips("config_mismatch") == 1
            assert b.metrics.sessions_adopted.value == 0
