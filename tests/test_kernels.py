"""Pallas fused corr lookup vs the XLA reference implementation.

Runs the kernel in interpreter mode (CPU) — same code path the TPU compiles.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.kernels import corr_lookup
from raft_stereo_tpu.models.corr import (build_corr_pyramid,
                                         lookup_pyramid_xla)


@pytest.fixture(autouse=True)
def _interpret_mode():
    corr_lookup._interpret_override = True
    yield
    corr_lookup._interpret_override = None


def _pyramid(rng, b=2, h=6, w=40, levels=3):
    vol = jnp.asarray(rng.normal(size=(b, h, w, w)).astype(np.float32))
    return build_corr_pyramid(vol, levels)


def test_fused_matches_xla_forward(rng):
    pyr = _pyramid(rng)
    b, h, w, _ = pyr[0].shape
    coords = jnp.asarray(
        rng.uniform(-3, w + 3, size=(b, h, w)).astype(np.float32))
    fused = corr_lookup.lookup_pyramid_fused(pyr, coords, radius=4)
    ref = lookup_pyramid_xla(pyr, coords, radius=4)
    assert fused.shape == ref.shape
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_fused_matches_xla_gradient(rng):
    pyr = _pyramid(rng, b=1, h=4, w=32, levels=2)
    b, h, w, _ = pyr[0].shape
    coords = jnp.asarray(
        rng.uniform(0, w, size=(b, h, w)).astype(np.float32))
    probe = jnp.asarray(rng.normal(size=(b, h, w, 2 * 9)).astype(np.float32))

    def loss_fused(vol):
        out = corr_lookup.lookup_pyramid_fused(
            build_corr_pyramid(vol, 2), coords, radius=4)
        return jnp.sum(out * probe)

    def loss_xla(vol):
        out = lookup_pyramid_xla(build_corr_pyramid(vol, 2), coords, radius=4)
        return jnp.sum(out * probe)

    vol0 = pyr[0]
    g_fused = jax.grad(loss_fused)(vol0)
    g_xla = jax.grad(loss_xla)(vol0)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_xla),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_fused_keeps_bf16(rng):
    pyr = [p.astype(jnp.bfloat16) for p in _pyramid(rng, levels=2)]
    b, h, w, _ = pyr[0].shape
    coords = jnp.asarray(rng.uniform(0, w, size=(b, h, w)).astype(np.float32))
    out = corr_lookup.lookup_pyramid_fused(pyr, coords, radius=4)
    assert out.dtype == jnp.bfloat16
    ref = lookup_pyramid_xla([p.astype(jnp.float32) for p in pyr], coords, 4)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), atol=0.15)


def test_fused_zero_padding(rng):
    """Far out-of-range centers sample all-zero windows."""
    pyr = _pyramid(rng, b=1, h=4, w=24, levels=1)
    b, h, w, _ = pyr[0].shape
    coords = jnp.full((b, h, w), -100.0)
    out = corr_lookup.lookup_pyramid_fused(pyr, coords, radius=4)
    np.testing.assert_array_equal(np.asarray(out), 0.0)


@pytest.mark.slow
def test_model_runs_with_fused_backend(rng):
    """End-to-end: reg_fused backend through the full model (interpret)."""
    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(hidden_dims=(32, 32, 32), fnet_dim=64,
                           corr_backend="reg_fused")
    model = RAFTStereo(cfg)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 64, 3)).astype(np.float32))
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 64, 3)).astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                           test_mode=True)
    low, up = model.apply(variables, img1, img2, iters=2, test_mode=True)
    assert np.isfinite(np.asarray(up)).all()

    # and the reg backend agrees (same weights, different lookup path)
    cfg_reg = RaftStereoConfig(hidden_dims=(32, 32, 32), fnet_dim=64,
                               corr_backend="reg")
    low2, up2 = RAFTStereo(cfg_reg).apply(variables, img1, img2, iters=2,
                                          test_mode=True)
    np.testing.assert_allclose(np.asarray(up), np.asarray(up2), atol=1e-3)
