"""Model-registry tests (tier-1, CPU): the round-21 multi-model surface.

Three layers:

* **ModelStore** — versioned publish/load round-trip, immutability,
  deep SHA-256 validation refusing a tampered blob, spec parsing.
* **Engine registry** — key NON-COLLISION across the full coordinate
  space (model, version, tier, family, quant never share a compile-cost
  or persist key) and the BITWISE single-model pin: an engine with no
  registered models produces exactly the pre-registry keys, fingerprint,
  and answers — and registering a non-default model changes none of
  them.  Plus hot registration (idempotent), default flip, typed
  retirement, and session pinning (no session ever sees two versions).
* **RolloutPolicy** — deterministic assignment, hysteresis demotion.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.models.raft_stereo import RAFTStereo
from raft_stereo_tpu.serving import ServeConfig, StereoService
from raft_stereo_tpu.serving.models import (ModelStore, ModelStoreError,
                                            ModelUnknown,
                                            ModelVersionExists,
                                            model_coord, parse_model_spec)

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")
ITERS = 1


@pytest.fixture(scope="module")
def tiny_model():
    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


@pytest.fixture(scope="module")
def tiny_model_v2(tiny_model):
    """Same architecture, different weights — a plausible new version."""
    cfg, variables = tiny_model
    v2 = jax.tree_util.tree_map(lambda a: a + 0.01, variables)
    return cfg, v2


def _pair(hw=(48, 64), seed=3):
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 255, hw + (3,), dtype=np.uint8)
    return left, np.roll(left, -3, axis=1)


# ------------------------------------------------------------- spec parsing
def test_parse_model_spec_and_coord():
    assert parse_model_spec("kitti@v2") == ("kitti", "v2")
    assert parse_model_spec("kitti") == ("kitti", None)
    assert model_coord("kitti", "v2") == "kitti@v2"
    for bad in ("", "a/b", "a@", "@v1", "a@b@c", "a b"):
        with pytest.raises(ValueError):
            parse_model_spec(bad)


# -------------------------------------------------------------- model store
def test_store_publish_load_roundtrip(tmp_path, tiny_model):
    cfg, variables = tiny_model
    store = ModelStore(str(tmp_path))
    store.publish("tiny", "v1", cfg, variables,
                  metadata={"note": "first"})
    assert store.has("tiny", "v1")
    assert store.versions("tiny") == ["v1"]
    assert store.list_models() == {"tiny": ["v1"]}
    reg = store.load("tiny", "v1", deep=True)
    assert reg.coord == "tiny@v1"
    assert reg.config == cfg
    assert reg.metadata["note"] == "first"
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(reg.variables)[0]),
        np.asarray(jax.tree_util.tree_leaves(variables)[0]))
    ok, reason = store.verify("tiny", "v1")
    assert ok, reason


def test_store_versions_are_immutable(tmp_path, tiny_model):
    cfg, variables = tiny_model
    store = ModelStore(str(tmp_path))
    store.publish("tiny", "v1", cfg, variables)
    with pytest.raises(ModelVersionExists):
        store.publish("tiny", "v1", cfg, variables)
    store.publish("tiny", "v1", cfg, variables, force=True)  # torn repair


def test_store_resolve_latest_and_unknown(tmp_path, tiny_model,
                                          tiny_model_v2):
    cfg, v1 = tiny_model
    _, v2 = tiny_model_v2
    store = ModelStore(str(tmp_path))
    store.publish("tiny", "v1", cfg, v1)
    store.publish("tiny", "v2", cfg, v2)
    assert store.latest_version("tiny") == "v2"
    assert store.resolve("tiny").version == "v2"   # bare name = newest
    assert store.resolve("tiny@v1").version == "v1"
    with pytest.raises(ModelStoreError):
        store.resolve("nope")


def test_store_deep_validation_refuses_tamper(tmp_path, tiny_model):
    cfg, variables = tiny_model
    store = ModelStore(str(tmp_path))
    path = store.publish("tiny", "v1", cfg, variables)
    import os
    victim = max(
        (os.path.join(d, f) for d, _, fs in os.walk(path) for f in fs
         if not f.startswith(("MANIFEST", "COMMIT"))),
        key=os.path.getsize)
    with open(victim, "r+b") as f:
        f.seek(0)
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    ok, reason = store.verify("tiny", "v1")
    assert not ok and reason
    with pytest.raises(ModelStoreError, match="deep validation"):
        store.load("tiny", "v1", deep=True)


# ---------------------------------------------------- engine key identity
@pytest.fixture()
def published_store(tmp_path_factory, tiny_model, tiny_model_v2):
    cfg, v1 = tiny_model
    _, v2 = tiny_model_v2
    root = str(tmp_path_factory.mktemp("model_store"))
    store = ModelStore(root)
    store.publish("tiny", "v1", cfg, v1)
    store.publish("tiny", "v2", cfg, v2)
    return root


def test_single_model_engine_is_bitwise_unchanged(tiny_model,
                                                  published_store):
    """The acceptance pin: with no registered models, every key and the
    exec-config fingerprint are exactly the pre-registry build's; and
    registering a NON-default model changes none of them, including the
    answer bytes of an implicit-model request."""
    cfg, variables = tiny_model
    left, right = _pair()
    serve = dict(max_batch=2, iters=ITERS)
    with StereoService(cfg, variables, ServeConfig(**serve)) as plain:
        cost_ref = plain._cost_key((64, 96), 1)
        disk_ref = plain._disk_key((64, 96), 1, 0, None)
        fp_ref = plain.exec_config_fingerprint()
        flow_ref = plain.infer(left, right, timeout=120).flow
    with StereoService(cfg, variables, ServeConfig(
            model_store_dir=published_store, **serve)) as svc:
        assert svc._cost_key((64, 96), 1) == cost_ref
        assert svc._disk_key((64, 96), 1, 0, None) == disk_ref
        assert svc.exec_config_fingerprint() == fp_ref
        assert ",model=" not in cost_ref
        res = svc.infer(left, right, timeout=120)
        assert res.model is None and res.model_version is None
        assert np.array_equal(res.flow, flow_ref)
        svc.register_model("tiny@v1", prewarm=False)
        # Registering (without the default flip) moves NOTHING on the
        # implicit surface.
        assert svc._cost_key((64, 96), 1) == cost_ref
        assert svc._disk_key((64, 96), 1, 0, None) == disk_ref
        assert svc.exec_config_fingerprint() == fp_ref
        assert np.array_equal(svc.infer(left, right, timeout=120).flow,
                              flow_ref)
        # The default FLIP is what changes the fingerprint (a handoff
        # exported under another default must re-enter typed-cold).
        svc.set_default_model("tiny")
        assert svc.exec_config_fingerprint() != fp_ref


def test_keys_never_collide_across_coordinates(tiny_model,
                                               published_store):
    """(model, version, tier, family, quant) all separate both the
    compile-cost key and the persist content key."""
    from raft_stereo_tpu.serving.engine import FAMILY_STATE, FAMILY_WARM

    cfg, variables = tiny_model
    with StereoService(cfg, variables, ServeConfig(
            max_batch=2, iters=ITERS,
            tiers=("interactive", "quality"),
            model_store_dir=published_store)) as svc:
        svc.register_model("tiny@v1", prewarm=False)
        b = (64, 96)
        cost_keys = [
            svc._cost_key(b, 1),
            svc._cost_key(b, 2),
            svc._cost_key(b, 1, tier="interactive"),
            svc._cost_key(b, 1, family=FAMILY_STATE),
            svc._cost_key(b, 1, family=FAMILY_WARM),
            svc._cost_key(b, 1, model="tiny"),
            svc._cost_key(b, 1, tier="interactive", model="tiny"),
            svc._cost_key(b, 1, family=FAMILY_STATE, model="tiny"),
        ]
        assert len(set(cost_keys)) == len(cost_keys)
        assert cost_keys[5].endswith(",model=tiny@v1)")
        disk_keys = [
            svc._disk_key(b, 1, 0, None),
            svc._disk_key(b, 2, 0, None),
            svc._disk_key(b, 1, 0, "interactive"),
            svc._disk_key(b, 1, 0, None, family=FAMILY_STATE),
            svc._disk_key(b, 1, 0, None, model="tiny"),
            svc._disk_key(b, 1, 0, "interactive", model="tiny"),
        ]
        assert len(set(disk_keys)) == len(disk_keys)
        v1_disk = svc._disk_key(b, 1, 0, None, model="tiny")
        v1_cost = svc._cost_key(b, 1, model="tiny")
        # A new VERSION under the same name gets new keys (same config,
        # same everything — only the version coordinate moved).
        svc.register_model("tiny@v2", prewarm=False)
        assert svc._disk_key(b, 1, 0, None, model="tiny") != v1_disk
        assert svc._cost_key(b, 1, model="tiny") != v1_cost
        off_cost = svc._cost_key(b, 1)
        off_disk = svc._disk_key(b, 1, 0, None)
    # Confidence (r24) is one more key coordinate: the same build with
    # --confidence compiles a DISTINCT program family, so both keys must
    # move — and with it off they must not mention it at all.
    assert ",conf" not in off_cost
    with StereoService(cfg, variables, ServeConfig(
            max_batch=2, iters=ITERS,
            tiers=("interactive", "quality"),
            confidence=True)) as conf_svc:
        conf_cost = conf_svc._cost_key(b, 1)
        assert conf_cost != off_cost and ",conf" in conf_cost
        assert conf_svc._disk_key(b, 1, 0, None) != off_disk


# ----------------------------------------------------- engine registration
def test_register_default_flip_retire_lifecycle(tiny_model,
                                                published_store):
    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables, ServeConfig(
            max_batch=2, iters=ITERS,
            model_store_dir=published_store)) as svc:
        out = svc.register_model("tiny@v1", prewarm=False)
        assert out["registered"] and out["default"] is None
        # idempotent re-register
        assert not svc.register_model("tiny@v1",
                                      prewarm=False)["registered"]
        res = svc.infer(left, right, model="tiny", timeout=120)
        assert (res.model, res.model_version) == ("tiny", "v1")
        st = svc.models_status()
        assert st["default"] is None
        assert [m["coord"] for m in st["registered"]] == ["tiny@v1"]
        # unknown model: typed, with the known list
        with pytest.raises(ModelUnknown) as ei:
            svc.infer(left, right, model="nope", timeout=120)
        assert ei.value.model == "nope" and ei.value.known == ["tiny"]
        # the default flip routes unnamed requests to the model
        svc.set_default_model("tiny")
        res = svc.infer(left, right, timeout=120)
        assert (res.model, res.model_version) == ("tiny", "v1")
        # retiring the default is refused typed (flip first)
        with pytest.raises(RuntimeError, match="default"):
            svc.retire_model("tiny")
        svc.set_default_model(None)
        assert svc.retire_model("tiny", timeout=10)["retired"]
        assert svc.models_status()["registered"] == []
        with pytest.raises(ModelUnknown):
            svc.infer(left, right, model="tiny", timeout=120)
        # the implicit model still serves
        assert svc.infer(left, right, timeout=120).model is None


def test_register_version_replace_answers_new_weights(tiny_model,
                                                      published_store):
    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables, ServeConfig(
            max_batch=2, iters=ITERS,
            model_store_dir=published_store)) as svc:
        svc.register_model("tiny@v1", prewarm=False)
        f1 = svc.infer(left, right, model="tiny", timeout=120)
        svc.register_model("tiny@v2", prewarm=False)   # live replace
        f2 = svc.infer(left, right, model="tiny", timeout=120)
        assert f2.model_version == "v2"
        assert not np.array_equal(f1.flow, f2.flow)


def test_boot_time_models_and_default(tiny_model, published_store):
    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables, ServeConfig(
            max_batch=2, iters=ITERS, models=("tiny@v1",),
            default_model="tiny",
            model_store_dir=published_store)) as svc:
        res = svc.infer(left, right, timeout=120)
        assert (res.model, res.model_version) == ("tiny", "v1")


def test_serve_config_models_validation(published_store):
    with pytest.raises(ValueError, match="store"):
        ServeConfig(models=("tiny@v1",))
    with pytest.raises(ValueError, match="duplicate"):
        ServeConfig(models=("tiny@v1", "tiny@v2"),
                    model_store_dir=published_store)
    with pytest.raises(ValueError, match="default_model"):
        ServeConfig(default_model="ghost",
                    model_store_dir=published_store)


# ------------------------------------------------------- session pinning
def test_session_pins_one_model_version(tiny_model, published_store):
    cfg, variables = tiny_model
    left, right = _pair()
    with StereoService(cfg, variables, ServeConfig(
            max_batch=2, iters=ITERS, sessions=True,
            model_store_dir=published_store)) as svc:
        svc.register_model("tiny@v1", prewarm=False)
        res = svc.infer_session("s1", left, right, model="tiny",
                                timeout=120)
        assert res.model == "tiny"
        # later frames inherit the pin without naming it
        assert svc.infer_session("s1", left, right,
                                 timeout=120).model == "tiny"
        # a session never spans two models: mid-stream switch is typed
        with pytest.raises(ValueError, match="pinned"):
            svc.infer_session("s1", left, right, model="other",
                              timeout=120)
        sess = svc.sessions.get("s1")
        assert sess is not None and sess.model == "tiny"
        assert "model" in sess.to_record()[0]
        # an implicit-model session's record carries NO model key —
        # its wire bytes are the pre-registry format
        svc.infer_session("s2", left, right, timeout=120)
        assert "model" not in svc.sessions.get("s2").to_record()[0]


# ------------------------------------------------------------ rollout policy
def _mk_policy(**cfg_kw):
    from raft_stereo_tpu.serving.fleet.rollout import (RolloutConfig,
                                                       RolloutPolicy)
    clock = {"t": 0.0}
    policy = RolloutPolicy(RolloutConfig(**cfg_kw),
                           clock=lambda: clock["t"])
    return policy, clock


def test_rollout_assignment_is_deterministic():
    policy, _ = _mk_policy()
    policy.set_canary("tiny@v2", 0.3, shadow_fraction=0.2)
    bodies = [f"req-{i}".encode() for i in range(400)]
    first = [policy.assign(b) for b in bodies]
    assert first == [policy.assign(b) for b in bodies]   # pure per body
    frac = sum(1 for a in first if a) / len(first)
    assert 0.15 < frac < 0.45     # ~0.3, hash-uniform
    # shadow sampling is independent of (and only on) the baseline arm
    baseline = [b for b, a in zip(bodies, first) if a is None]
    shadows = [policy.wants_shadow(b) for b in baseline]
    assert shadows == [policy.wants_shadow(b) for b in baseline]
    assert 0 < sum(shadows) < len(shadows)


def test_rollout_requires_explicit_version():
    policy, _ = _mk_policy()
    with pytest.raises(ValueError, match="version"):
        policy.set_canary("tiny", 0.1)
    with pytest.raises(ValueError):
        policy.set_canary("tiny@v2", 1.5)


def test_rollout_demotion_needs_sustained_regression():
    policy, clock = _mk_policy(min_samples=4, error_threshold=0.5,
                               demote_after_s=2.0)
    policy.set_canary("tiny@v2", 0.5)
    for _ in range(4):
        policy.note_canary_result(False)
    assert not policy.status()["demoted"]      # verdict, but no dwell yet
    assert policy.assign(b"x") in (None, "tiny")
    clock["t"] = 3.0
    assert policy.poll()                       # dwell elapsed -> demoted
    st = policy.status()
    assert st["demoted"] and "error rate" in st["demoted_reason"]
    assert st["fraction"] == 0.0
    assert all(policy.assign(f"y{i}".encode()) is None for i in range(50))
    assert not policy.poll()                   # one-way: fires once


def test_rollout_recovery_resets_dwell():
    policy, clock = _mk_policy(min_samples=4, epe_threshold=1.0,
                               demote_after_s=2.0, window=8)
    policy.set_canary("tiny@v2", 0.5)
    for _ in range(4):
        policy.note_shadow_epe(5.0)            # regressing
    clock["t"] = 1.0
    for _ in range(8):
        policy.note_shadow_epe(0.01)           # bad samples age out
    clock["t"] = 10.0
    assert not policy.poll() and not policy.status()["demoted"]
    # re-arming after a demotion clears the evidence + demoted latch
    for _ in range(12):
        policy.note_shadow_epe(9.0)
    clock["t"] = 20.0
    policy.poll()
    assert policy.status()["demoted"]
    policy.set_canary("tiny@v3", 0.1)
    st = policy.status()
    assert not st["demoted"] and st["model"] == "tiny@v3"
