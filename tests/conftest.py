"""Test configuration: run all tests on CPU with 8 virtual devices.

Multi-device sharding tests follow SURVEY.md §4's strategy: CPU-backed JAX
standing in for TPU via ``--xla_force_host_platform_device_count``.

Note: this environment's sitecustomize registers a remote-TPU PJRT plugin
("axon") at interpreter startup — before conftest runs — and that plugin is
initialized even under ``JAX_PLATFORMS=cpu``.  The machine has exactly one
remote TPU claim, so a test suite touching it would serialize against (and
wedge behind) any other process using the chip.  We deregister the plugin
here so tests are hermetic and CPU-only.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

try:  # deregister the remote-TPU plugin if sitecustomize installed it
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - plugin absent in other environments
    pass

import jax

# jax.config latched JAX_PLATFORMS at import time (sitecustomize imports jax
# before conftest) — update it explicitly.
jax.config.update("jax_platforms", "cpu")

assert jax.devices()[0].platform == "cpu", "tests must run on CPU"
assert len(jax.devices()) >= 8, "tests expect >= 8 virtual CPU devices"

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def pytest_collection_modifyitems(config, items):
    """Two test tiers (VERDICT round 1 #8): everything not marked ``slow``
    is auto-marked ``quick``, so ``pytest -m quick`` is the <60s regression
    smoke and ``pytest -m slow`` the heavy full-model/sharded tier."""
    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.quick)
