"""Test configuration: run all tests on CPU with 8 virtual devices.

Multi-device sharding tests follow SURVEY.md §4's strategy: CPU-backed JAX
standing in for TPU via ``--xla_force_host_platform_device_count``.  The
hermetic-CPU setup itself (including deregistering this environment's
remote-TPU "axon" plugin) lives in tests/_hermetic.py, shared with the
distributed-test subprocess workers.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
# repo root too: tests import the root-level bench modules (e.g.
# bench_loader's tree builder), which are tracked sources, so the suite
# must resolve them when pytest is invoked from any directory.
sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
from _hermetic import force_cpu  # noqa: E402

jax = force_cpu(8)

assert jax.devices()[0].platform == "cpu", "tests must run on CPU"
assert len(jax.devices()) >= 8, "tests expect >= 8 virtual CPU devices"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def require_corr_mesh():
    """Capability-probe gate for tests composing a corr mesh axis with
    another axis (partial-manual shard_map on a two-axis mesh): jax
    0.4.x's CPU backend rejects the lowering (PartitionId UNIMPLEMENTED
    — ROADMAP item 2), so on such backends the test SKIPS with the typed
    reason instead of reading as pre-existing red.  On backends where the
    probe passes (TPU, newer jax) the test runs — no signal lost."""
    from raft_stereo_tpu.parallel.compat import partial_manual_mesh_capability

    ok, reason = partial_manual_mesh_capability()
    if not ok:
        pytest.skip(reason)


def pytest_collection_modifyitems(config, items):
    """Two test tiers (VERDICT round 1 #8): everything not marked ``slow``
    is auto-marked ``quick``, so ``pytest -m quick`` is the <60s regression
    smoke and ``pytest -m slow`` the heavy full-model/sharded tier."""
    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.quick)
