"""Compiler-cost & efficiency layer (telemetry/costs.py): the AOT compile
registry, backend-degradation contract, runner cache eviction telemetry,
padding-waste accounting, MFU gauges, and the per-phase cost report.

The load-bearing assertions: with cost telemetry ON, chain-mode serving
stays bitwise-identical to solo inference (the AOT executable runs the
same program the jit path compiles), and with it OFF nothing in the
dispatch path changes (the registry-less runner keeps plain ``jax.jit``
callables).  A backend whose ``cost_analysis``/``memory_analysis`` raises
or returns nothing must degrade to a compile-time-only record, never an
error on the dispatch path.
"""

import json
import logging
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.telemetry.costs import (CompileRegistry, MfuMeter,
                                             aot_cost_summary,
                                             classify_bound,
                                             executable_cost,
                                             peak_flops_for,
                                             ridge_flops_per_byte)
from raft_stereo_tpu.telemetry.registry import MetricsRegistry

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")
ITERS = 1


@pytest.fixture(scope="module")
def tiny_model():
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    img = jnp.zeros((1, 48, 64, 3), jnp.float32)
    variables = jax.jit(lambda r: model.init(r, img, img, iters=1,
                                             test_mode=True)
                        )(jax.random.PRNGKey(0))
    return cfg, jax.device_get(variables)


# ------------------------------------------------------------- registry core
def test_instrumented_fn_records_cost_and_matches_jit():
    registry = MetricsRegistry()
    costs = CompileRegistry(registry=registry)
    f = jax.jit(lambda x: (x @ x).sum())
    inst = costs.instrument(f, key="t.mm", site="bench")
    x = jnp.ones((32, 32))
    assert float(inst(x)) == float(f(x))
    assert float(inst(x)) == float(f(x))  # cached-executable path

    rec = costs.get("t.mm")
    assert rec is not None and rec.site == "bench"
    assert rec.flops and rec.flops > 0
    assert rec.bytes_accessed and rec.bytes_accessed > 0
    assert rec.memory["argument_size_in_bytes"] == 32 * 32 * 4
    assert rec.compile_s > 0 and not rec.degraded
    assert rec.arithmetic_intensity == rec.flops / rec.bytes_accessed
    # one executable, one compile, instruments live
    assert costs.to_json()["count"] == 1
    assert registry.get("compiles_total").value == 1
    assert registry.get("compile_seconds").count == 1

    # shape change re-lowers (a recorded recompile), results stay correct
    y = jnp.full((16, 16), 2.0)
    assert float(inst(y)) == float(f(y))
    assert registry.get("compiles_total").value == 2


def test_record_survives_metric_registry_absence():
    costs = CompileRegistry()  # no MetricsRegistry attached at all
    f = jax.jit(lambda x: x + 1)
    inst = costs.instrument(f, key="t.add", site="eval")
    np.testing.assert_array_equal(np.asarray(inst(jnp.zeros(4))), np.ones(4))
    assert costs.get("t.add").flops is not None


# ------------------------------------------------- degradation (satellite)
class _Broken:
    """Compiled-alike whose analyses fail like older-jax/odd backends."""

    def __init__(self, cost_exc=True, mem_exc=True):
        self._cost_exc, self._mem_exc = cost_exc, mem_exc

    def cost_analysis(self):
        if self._cost_exc:
            raise NotImplementedError("backend reports no costs")
        return []          # empty list: another observed older-jax shape

    def memory_analysis(self):
        if self._mem_exc:
            raise NotImplementedError("backend reports no memory stats")
        return None


def test_executable_cost_degrades_without_raising():
    for broken in (_Broken(), _Broken(cost_exc=False),
                   _Broken(mem_exc=False)):
        out = executable_cost(broken)
        assert out["degraded"] is True
        assert out["flops"] is None and out["memory"] is None


def test_dispatch_path_survives_broken_cost_analysis(monkeypatch):
    """cost_analysis raising on a REAL compiled executable yields a
    degraded-but-valid record and an unchanged result — the satellite
    contract that cost accounting can never fail a dispatch."""
    f = jax.jit(lambda x: x * 2)
    compiled_cls = type(f.lower(jnp.ones(3)).compile())

    def _boom(self):
        raise RuntimeError("no costs on this backend")

    monkeypatch.setattr(compiled_cls, "cost_analysis", _boom)
    monkeypatch.setattr(compiled_cls, "memory_analysis", _boom)
    costs = CompileRegistry(registry=MetricsRegistry())
    inst = costs.instrument(jax.jit(lambda x: x * 2), key="t.deg",
                            site="eval")
    np.testing.assert_array_equal(np.asarray(inst(jnp.ones(3))),
                                  np.full(3, 2.0))
    rec = costs.get("t.deg")
    assert rec.degraded and rec.flops is None and rec.memory is None
    assert rec.compile_s > 0  # compile-time-only record


def test_aot_compile_falls_back_when_lowering_fails():
    class _NoAot:
        def lower(self, *a, **k):
            raise TypeError("no AOT on this stage")

        def __call__(self, x):
            return x + 41

    costs = CompileRegistry(registry=MetricsRegistry())
    fn = costs.aot_compile(_NoAot(), jnp.ones(()), key="t.noaot",
                           site="train")
    assert float(fn(jnp.ones(()))) == 42.0  # the plain callable came back
    assert costs.get("t.noaot").degraded


def test_aot_cost_summary_bench_denominator():
    """bench.py attaches this summary to its JSON record."""
    s = aot_cost_summary(jax.jit(lambda x: (x @ x).sum()), jnp.ones((8, 8)))
    assert s["flops"] > 0 and s["bytes_accessed"] > 0
    assert s["compile_s"] > 0 and not s["degraded"]
    assert s["arithmetic_intensity"] == s["flops"] / s["bytes_accessed"]
    json.dumps(s)  # must ride a bench record as-is


# --------------------------------------------- runner cache (satellite)
def test_runner_eviction_is_logged_and_counted(tiny_model, caplog):
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    registry = MetricsRegistry()
    costs = CompileRegistry(registry=registry)
    runner = InferenceRunner(cfg, variables, iters=ITERS,
                             max_cached_shapes=2, cost_registry=costs)
    # _forward_for only BUILDS the per-shape callables (no execution), so
    # filling the cache past its bound is cheap.
    shapes = [(32, 64), (64, 64), (64, 96), (96, 96)]
    with caplog.at_level(logging.INFO, logger="raft_stereo_tpu.eval.runner"):
        for s in shapes:
            runner._forward_for(s)
    # oldest-first: the two oldest shapes were evicted, newest two remain
    assert list(runner._compiled) == [(s, 1) for s in shapes[2:]]
    assert registry.get("runner_compile_evictions_total").value == 2
    assert registry.get("runner_compile_cache_size").value == 2
    evict_logs = [r for r in caplog.records if "evicting oldest" in r.message]
    assert len(evict_logs) == 2
    assert "(32, 64)" in evict_logs[0].getMessage()  # the oldest went first

    # registry-less runner: same logging, no instruments, plain jit cached
    bare = InferenceRunner(cfg, variables, iters=ITERS, max_cached_shapes=1)
    bare._forward_for((32, 64))
    caplog.clear()
    with caplog.at_level(logging.INFO, logger="raft_stereo_tpu.eval.runner"):
        bare._forward_for((64, 64))
    assert any("evicting oldest" in r.message for r in caplog.records)
    from raft_stereo_tpu.telemetry.costs import _InstrumentedFn
    assert not isinstance(bare._forward_for((64, 64)), _InstrumentedFn)
    assert isinstance(runner._forward_for((96, 96)), _InstrumentedFn)


# --------------------------------------------------------- peaks and MFU
def test_peak_table_and_override():
    assert peak_flops_for("TPU v5 lite") == 197e12
    assert peak_flops_for("TPU v4") == 275e12
    assert peak_flops_for("weird accelerator") is None
    assert peak_flops_for("cpu", override_tflops=2.0) == 2e12
    ridge, src = ridge_flops_per_byte(197e12, 819e9)
    assert src == "device" and ridge == pytest.approx(240.5, abs=0.5)
    _, src = ridge_flops_per_byte(None, None)
    assert src == "default"
    assert classify_bound(1e9, 1e6, 240.0) == "compute"
    assert classify_bound(1e6, 1e6, 240.0) == "memory"
    assert classify_bound(None, 1e6, 240.0) == "unknown"


def test_mfu_meter_window_math():
    from raft_stereo_tpu.telemetry.registry import Gauge

    mfu, achieved = Gauge("m"), Gauge("a")
    meter = MfuMeter(mfu, peak_flops=100.0, achieved_gauge=achieved,
                     window_s=60.0)
    meter.note(500.0, now=100.0)   # first note: no elapsed window yet
    assert mfu.value == 0.0
    meter.note(500.0, now=110.0)   # 1000 flops over 10 s = 100 flop/s
    assert achieved.value == pytest.approx(100.0)
    assert mfu.value == pytest.approx(1.0)

    unknown = MfuMeter(Gauge("m2"), peak_flops=None)
    unknown.note(500.0, now=0.0)
    unknown.note(500.0, now=10.0)
    assert unknown.gauge.value == 0.0  # no fictional MFU without a peak


# ------------------------------------------- labeled instrument families
def test_registry_label_families_render_grouped():
    r = MetricsRegistry()
    a = r.counter("px_total", "pixels", labels={"bucket": "64x96"})
    b = r.counter("px_total", "pixels", labels={"bucket": "32x64"})
    with pytest.raises(ValueError):
        r.counter("px_total", "pixels", labels={"bucket": "64x96"})
    a.inc(5), b.inc(7)
    text = r.render_text()
    assert 'px_total{bucket="64x96"} 5' in text
    assert 'px_total{bucket="32x64"} 7' in text
    # exactly one HELP/TYPE header for the family, samples grouped under it
    assert text.count("# TYPE px_total counter") == 1
    assert r.get("px_total", labels={"bucket": "32x64"}) is b
    assert r.get("px_total") in (a, b)


# ----------------------------------------------------- serving integration
def test_serving_cost_telemetry_end_to_end(tiny_model, tmp_path):
    """Cost telemetry ON: chain-mode results stay bitwise-equal to a solo
    registry-less runner, /debug/compiles lists the bucket executables
    with cost+memory fields, padding waste is accounted per bucket, the
    MFU plumbing sees the dispatched flops, and the first compile of a
    bucket emits a run event (the serving satellite)."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer
    from raft_stereo_tpu.telemetry import EventLog, replay

    cfg, variables = tiny_model
    rng = np.random.default_rng(7)
    left = rng.integers(0, 255, (60, 90, 3), np.uint8)    # pads to 64x96
    right = rng.integers(0, 255, (60, 90, 3), np.uint8)
    small_l = rng.integers(0, 255, (30, 40, 3), np.uint8)  # pads to 32x64
    small_r = rng.integers(0, 255, (30, 40, 3), np.uint8)

    events = EventLog(str(tmp_path / "serve-events.jsonl"))
    svc = StereoService(cfg, variables,
                        ServeConfig(iters=ITERS, max_wait_ms=1.0,
                                    cost_telemetry=True,
                                    device_peak_tflops=0.001))
    svc.costs.events = events
    server = StereoHTTPServer(svc, port=0).start()
    try:
        res = svc.infer(left, right, timeout=120)
        svc.infer(small_l, small_r, timeout=120)

        solo = InferenceRunner(cfg, variables, iters=ITERS)
        flow, _ = solo(left, right)
        np.testing.assert_array_equal(res.flow, flow)  # bitwise, AOT vs jit

        compiles = json.load(urllib.request.urlopen(
            server.url + "/debug/compiles", timeout=10))
        assert compiles["count"] == 2
        by_key = {e["key"]: e for e in compiles["executables"]}
        assert set(by_key) == {"serving.forward(64x96,b1)",
                               "serving.forward(32x64,b1)"}
        for e in by_key.values():
            assert e["flops"] > 0 and e["bytes_accessed"] > 0
            assert e["memory"]["argument_size_in_bytes"] > 0
            assert not e["degraded"]

        text = urllib.request.urlopen(server.url + "/metrics",
                                      timeout=10).read().decode()
        # mixed-shape load: nonzero waste histogram + per-bucket counters
        assert "serve_padding_waste_count 2" in text
        assert ('serve_bucket_real_pixels_total{bucket="64x96"} '
                f"{60 * 90}") in text
        assert ('serve_bucket_pad_pixels_total{bucket="64x96"} '
                f"{64 * 96 - 60 * 90}") in text
        assert text.count("# TYPE serve_bucket_pad_pixels_total") == 1
        waste = svc.metrics.padding_waste
        assert 0 < waste.mean() < 1
        # MFU numerator: both dispatches' flops counted, gauge moved
        total_flops = sum(e["flops"] for e in by_key.values())
        assert svc.metrics.dispatched_flops.value == pytest.approx(
            total_flops)
        assert svc.metrics.achieved_flops_per_s.value >= 0

        kinds = [e for e in replay(events.path) if e["event"] == "compile"]
        assert len(kinds) == 2 and kinds[0]["site"] == "serving"
        assert kinds[0]["flops"] > 0
    finally:
        server.shutdown()
        svc.close()
        events.close()


def test_cost_telemetry_off_keeps_plain_jit_dispatch(tiny_model):
    """The hard constraint: registry-off leaves the dispatch path
    untouched — the workers cache the plain jitted callables and no cost
    instruments register."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables, ServeConfig(iters=ITERS))
    try:
        assert svc.costs is None and svc._mfu is None
        assert svc.metrics.registry.get("compiles_total") is None
        fwd = svc._forward_for((32, 64), batch=1)
        from raft_stereo_tpu.telemetry.costs import _InstrumentedFn
        assert not isinstance(fwd, _InstrumentedFn)
    finally:
        svc.close()


def test_debug_compiles_404_without_registry():
    from raft_stereo_tpu.telemetry.http import handle_debug_get

    replies = []
    handled = handle_debug_get(
        "/debug/compiles", "", None, None, None,
        lambda *a: replies.append(a),
        lambda code, obj: replies.append((code, obj)), costs=None)
    assert handled and replies[0][0] == 404


# ---------------------------------------------------- training integration
def test_train_step_cost_instrumented(tmp_path):
    """The instrumented train step lands in the registry with flops; the
    drain turns them into train_step_flops / achieved-FLOP/s gauges and
    the step_stats event carries step_flops; recompile detection stays at
    zero (the step-0 AOT compile is not a recompile)."""
    from raft_stereo_tpu.config import TrainConfig
    from raft_stereo_tpu.data.loader import StereoLoader
    from raft_stereo_tpu.telemetry import (CompileRegistry, EventLog,
                                           TrainTelemetry, replay)
    from raft_stereo_tpu.training.train_loop import train

    class _Synthetic:
        def __len__(self):
            return 4

        def __getitem__(self, i, epoch=0):
            img = np.full((32, 64, 3), float(i), np.float32)
            return {"image1": img, "image2": img,
                    "flow": np.full((32, 64), -2.0, np.float32),
                    "valid": np.ones((32, 64), np.float32)}

    registry = MetricsRegistry()
    events = EventLog(str(tmp_path / "events.jsonl"))
    costs = CompileRegistry(registry=registry, events=events,
                            device_peak_tflops=0.001)
    telemetry = TrainTelemetry(registry=registry, events=events, costs=costs)
    model_cfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,),
                                 fnet_dim=64, fnet_norm="none")
    train_cfg = TrainConfig(batch_size=2, train_iters=2, num_steps=3,
                            image_size=(32, 64), validation_frequency=10_000,
                            data_parallel=1)
    loader = StereoLoader(_Synthetic(), batch_size=2, num_workers=0,
                          shuffle=False)
    state = train(model_cfg, train_cfg, name="cost-test",
                  checkpoint_dir=str(tmp_path / "ckpt"),
                  log_dir=str(tmp_path / "runs"), loader=loader,
                  use_mesh=False, telemetry=telemetry)
    events.close()
    assert int(state.step) == 3

    rec = costs.get("train.step")
    assert rec is not None and rec.flops > 0 and not rec.degraded
    assert registry.get("train_step_flops").value == rec.flops
    assert registry.get("train_achieved_flops_per_s").value > 0
    assert registry.get("train_mfu").value > 0  # peak was given
    assert registry.get("train_recompiles_total").value == 0

    recs = list(replay(events.path))
    compile_events = [e for e in recs if e["event"] == "compile"]
    assert any(e.get("key") == "train.step" and e.get("flops")
               for e in compile_events)
    stats = [e for e in recs if e["event"] == "step_stats"]
    assert stats and stats[-1]["step_flops"] == rec.flops
    assert stats[-1]["mfu"] > 0


# ----------------------------------------------------- cost report tool
def test_cost_report_tool_phases_sum_and_classify(tmp_path):
    """Acceptance: per-phase flop totals sum to the whole-model
    executable's flops within tolerance, and every phase gets a roofline
    classification."""
    import tools.cost_report as cost_report

    out = str(tmp_path / "COST_REPORT_test.json")
    assert cost_report.main(["--config", "tiny", "--height", "64",
                             "--width", "96", "--iters", "2",
                             "--out", out]) == 0
    with open(out) as f:
        rep = json.load(f)
    assert rep["schema_version"] >= 1 and rep["metric"] == "cost_report"
    phases = rep["phases"]
    assert set(phases) == {"fnet", "cnet", "corr_pyramid", "gru_iter",
                           "upsample", "other"}
    for name, p in phases.items():
        assert p["bound"] in ("compute", "memory"), name
        assert p["flops"] is not None, name
    assert phases["gru_iter"]["flops"] > 0
    assert phases["gru_iter"]["per_iteration"]["flops"] > 0
    assert rep["sum_check"]["rel_err"] < 1e-6
    assert rep["whole_model"]["memory"]["argument_size_in_bytes"] > 0
    # the deployed scan executable is recorded with its caveat
    assert "deployed_scan_executable" in rep


def test_unrolled_gru_matches_scan(tiny_model):
    """unroll_gru (the cost tool's compile subject) runs the same math as
    the deployed scan."""
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg, variables = tiny_model
    model = RAFTStereo(cfg)
    rng = np.random.default_rng(3)
    i1 = jnp.asarray(rng.uniform(0, 255, (1, 48, 64, 3)), jnp.float32)
    i2 = jnp.asarray(rng.uniform(0, 255, (1, 48, 64, 3)), jnp.float32)
    d_scan, f_scan = model.apply(variables, i1, i2, iters=2, test_mode=True)
    d_un, f_un = model.apply(variables, i1, i2, iters=2, test_mode=True,
                             unroll_gru=True)
    np.testing.assert_allclose(np.asarray(f_scan), np.asarray(f_un),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(d_scan), np.asarray(d_un),
                               atol=1e-5, rtol=1e-5)
