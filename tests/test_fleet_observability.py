"""Fleet observability (round 23): cross-process trace propagation,
metrics federation, SLO burn-rate alerting, and the coordinated
flight-recorder dump.

Covers the ISSUE-18 acceptance surface:

* traceparent codec round-trip + malformed-input rejection, and
  ``adopt_trace`` overriding the local sample rate (the upstream
  sampling decision wins);
* federation text transforms — quote-aware label injection, label-value
  escaping round-trip, HELP/TYPE dedup across replicas — plus the
  ``MetricsFederator`` edge cases (replica dies mid-scrape → stale
  marker without a request-path stall; aged-out series vanish);
* ``BurnRateTracker`` window math under a fake clock (restart clamp,
  budget normalisation) and ``SloWatchdog`` trip/hysteresis/dump;
* the end-to-end proof: ONE trace id appearing in the router's span ring
  AND the replica's, merged by ``GET /debug/spans?trace=<id>`` — across
  a transport failover retry (two ``route.forward`` children under one
  trace) — and on a REAL engine replica (serve.request adopted as a
  child of the router's span);
* router error paths (503 ``no_replicas_ready``, 410 ``session_lost``)
  carrying ``X-Trace-Id`` and counting toward the SLO error totals.
"""

import io
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_stereo_tpu.serving.fleet import (FleetRouter, MetricsFederator,
                                           RouterConfig, RouterHTTPServer,
                                           inject_label,
                                           relabel_exposition)
from raft_stereo_tpu.telemetry.registry import (MetricsRegistry,
                                                escape_label_value,
                                                unescape_label_value)
from raft_stereo_tpu.telemetry.slo import BurnRateTracker, SloWatchdog
from raft_stereo_tpu.telemetry.spans import (SpanTracer, TraceContext,
                                             decode_traceparent,
                                             encode_traceparent)

from tests.test_fleet import (FakeClock, StubReplica, TINY, _get, _post,
                              fleet3, tiny_model)  # noqa: F401  (fixtures)


# ------------------------------------------------------- traceparent codec
def test_traceparent_round_trip():
    hdr = encode_traceparent("ab" * 8, "cd" * 4)
    assert hdr == "00-abababababababab-cdcdcdcd-01"
    ctx = decode_traceparent(hdr)
    assert ctx == TraceContext("ab" * 8, "cd" * 4, sampled=True)


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-xyz-abc-01", "00-abab-01",
    "00-" + "0" * 16 + "-cdcdcdcd-01",      # all-zero trace id invalid
    "00-abababababababab-" + "0" * 8 + "-01",  # all-zero span id invalid
    "zz-abababababababab-cdcdcdcd-01",      # non-hex version
])
def test_traceparent_malformed_decodes_to_none(bad):
    assert decode_traceparent(bad) is None


def test_traceparent_lenient_widths_and_flags():
    # Foreign tracers emit 32-hex trace / 16-hex span ids; the decoder
    # is lenient on widths and only the sampled bit of flags matters.
    ctx = decode_traceparent(f"00-{'5' * 32}-{'7' * 16}-00")
    assert ctx is not None
    assert ctx.trace_id == "5" * 32 and ctx.parent_span_id == "7" * 16
    assert ctx.sampled is False


def test_adopt_trace_overrides_local_sample_rate():
    tracer = SpanTracer(sample_rate=0.0)
    assert tracer.start_trace("x") is None, "rate 0 must not sample"
    ctx = decode_traceparent(encode_traceparent("ab" * 8, "cd" * 4))
    trace = tracer.adopt_trace(ctx, "serve.request", bucket="(48, 64)")
    assert trace is not None and trace.trace_id == "ab" * 8
    tracer.finish_trace(trace)
    spans = [s for s in tracer.spans() if s.trace_id == "ab" * 8]
    assert len(spans) == 1
    # The adopted root parents to the UPSTREAM span id — the property
    # that stitches the replica subtree under the router's forward span.
    assert spans[0].parent_id == "cd" * 4
    assert spans[0].name == "serve.request"


def test_adopt_trace_none_context_falls_back_to_sampler():
    tracer = SpanTracer(sample_rate=0.0)
    assert tracer.adopt_trace(None, "serve.request") is None


# ------------------------------------------------- federation text engine
def test_inject_label_no_labelset():
    assert inject_label("metric 1", "replica", "r0") == \
        'metric{replica="r0"} 1'


def test_inject_label_existing_labelset():
    assert inject_label('m{a="b"} 1', "replica", "r0") == \
        'm{replica="r0",a="b"} 1'


def test_inject_label_empty_labelset():
    assert inject_label("m{} 1", "replica", "r0") == 'm{replica="r0"} 1'


def test_inject_label_brace_inside_quoted_value():
    # A `{` inside a quoted label VALUE is legal exposition text and
    # must not be mistaken for the labelset opener.
    line = 'm{path="/v1/{id}"} 3'
    assert inject_label(line, "replica", "r0") == \
        'm{replica="r0",path="/v1/{id}"} 3'


def test_inject_label_value_escaping_round_trips():
    # Satellite 3: replica names with quotes/backslashes/newlines
    # round-trip through the registry's own escape helpers.
    nasty = 'we"ird\\na\nme'
    out = inject_label("m 1", "replica", nasty)
    quoted = out.split('replica="', 1)[1].rsplit('"}', 1)[0]
    assert unescape_label_value(quoted) == nasty
    assert "\n" not in out, "raw newline would corrupt the exposition"


def test_relabel_exposition_dedups_help_type_across_replicas():
    # Satellite 3: two replicas exposing the SAME family merge under one
    # HELP/TYPE header, their samples distinguishable only by replica=.
    text = ("# HELP reqs_total Requests.\n"
            "# TYPE reqs_total counter\n"
            "reqs_total 5\n")
    seen = {}
    out_a = relabel_exposition(text, "replica", "a", seen)
    out_b = relabel_exposition(text, "replica", "b", seen)
    merged = out_a + out_b
    assert merged.count("# HELP reqs_total Requests.") == 1
    assert merged.count("# TYPE reqs_total counter") == 1
    assert 'reqs_total{replica="a"} 5' in merged
    assert 'reqs_total{replica="b"} 5' in merged


class _ScriptedReplica:
    """Duck-typed federation target: get_metrics returns scripted text
    or raises."""

    def __init__(self, text):
        self.text = text
        self.dead = False
        self.calls = 0

    def get_metrics(self, timeout):
        self.calls += 1
        if self.dead:
            raise ConnectionError("scripted death")
        return self.text


def test_federator_marks_dead_replica_stale_then_ages_out():
    clock = FakeClock()
    a = _ScriptedReplica("m_a 1\n")
    b = _ScriptedReplica("m_b 2\n")
    fed = MetricsFederator(lambda: [("a", a), ("b", b)], poll_s=1.0,
                           timeout_s=0.5, stale_after_s=30.0, clock=clock)
    assert fed.scrape_once() == {"a": True, "b": True}
    text = fed.render()
    assert 'fleet_federation_up{replica="a"} 1' in text
    assert 'm_a{replica="a"} 1' in text and 'm_b{replica="b"} 2' in text

    # b dies mid-scrape: its entry flips stale (up 0) but the LAST-GOOD
    # series stay exposed, and render() never blocks on the dead socket.
    b.dead = True
    clock.t += 5.0
    assert fed.scrape_once() == {"a": True, "b": False}
    text = fed.render()
    assert 'fleet_federation_up{replica="b"} 0' in text
    assert 'm_b{replica="b"} 2' in text, "last-good series stay visible"
    assert fed.status()["replicas"]["b"]["fresh"] is False

    # Past stale_after_s the series vanish; only the down marker stays.
    clock.t += 31.0
    text = fed.render()
    assert 'fleet_federation_up{replica="b"} 0' in text
    assert "m_b" not in text, "aged-out series must vanish"
    assert 'm_a{replica="a"} 1' not in text  # a aged out too (no scrape)


def test_federator_render_dedups_families_across_replicas_and_own():
    clock = FakeClock()
    fam = ("# HELP x_total X.\n# TYPE x_total counter\nx_total 1\n")
    a, b = _ScriptedReplica(fam), _ScriptedReplica(fam)
    fed = MetricsFederator(lambda: [("a", a), ("b", b)], poll_s=1.0,
                           timeout_s=0.5, clock=clock)
    fed.scrape_once()
    text = fed.render(own_text="# HELP own_total O.\n"
                               "# TYPE own_total counter\nown_total 9\n")
    assert text.count("# HELP x_total") == 1
    assert "own_total 9" in text and 'x_total{replica="a"} 1' in text


# --------------------------------------------------------- SLO burn rates
def test_burn_rate_tracker_windows_and_clamp():
    clock = FakeClock()
    reg = MetricsRegistry()
    tr = BurnRateTracker(availability=0.99, registry=reg, clock=clock,
                         windows=(("5m", 300.0), ("1h", 3600.0)))
    assert tr.sample(0, 0) == {"5m": 0.0, "1h": 0.0}
    clock.t += 100.0
    # 100 good, 1 bad → bad fraction 1/101 ≈ 0.0099, budget 0.01 → ~0.99
    burns = tr.sample(100, 1)
    assert burns["5m"] == pytest.approx(1 / 101 / 0.01)
    assert burns["1h"] == burns["5m"]
    text = reg.render_text()
    assert 'fleet_slo_burn_rate{window="5m"}' in text

    # A replica restart regresses the totals; deltas clamp at zero
    # instead of manufacturing negative traffic.
    clock.t += 100.0
    burns = tr.sample(10, 0)
    assert burns["5m"] == 0.0 and burns["1h"] == 0.0

    with pytest.raises(ValueError):
        BurnRateTracker(availability=1.0)


def test_burn_rate_fast_window_forgets_old_errors():
    clock = FakeClock()
    tr = BurnRateTracker(availability=0.999, clock=clock)
    tr.sample(0, 0)
    clock.t += 60.0
    tr.sample(100, 100)          # a cliff: 50% bad
    clock.t += 400.0             # past the 5m window, inside 1h
    burns = tr.sample(300, 100)  # 200 new good, 0 new bad
    assert burns["5m"] == 0.0, "the cliff left the fast window"
    assert burns["1h"] > 0.0, "…but still burns the slow one"


class _Sink:
    def __init__(self):
        self.fired = []

    def fire(self, kind, **detail):
        self.fired.append((kind, detail))


def test_slo_watchdog_requires_both_windows_then_rearms():
    clock = FakeClock()
    tr = BurnRateTracker(availability=0.999, clock=clock)
    sink = _Sink()
    dumps = []
    wd = SloWatchdog(tr, sink, fast_burn=14.4, slow_burn=6.0,
                     dump_fn=lambda tid, d: dumps.append(tid) or
                     {"trigger": tid},
                     id_fn=lambda: "feedbeef00000001")
    # Fast window alone breaching must NOT page (a blip).
    assert wd.check({"5m": 20.0, "1h": 1.0}) is None
    assert not sink.fired and not dumps
    # Both breaching: one page, one coordinated dump, versioned detail.
    rec = wd.check({"5m": 20.0, "1h": 7.0})
    assert rec is not None
    assert rec["trigger_trace_id"] == "feedbeef00000001"
    assert rec["fleet_dump"] == {"trigger": "feedbeef00000001"}
    assert sink.fired[0][0] == "slo_burn"
    assert dumps == ["feedbeef00000001"]
    # Still breaching: latched, no double fire.
    assert wd.check({"5m": 20.0, "1h": 7.0}) is None
    # Dropping below threshold but above HALF threshold: still latched.
    assert wd.check({"5m": 10.0, "1h": 4.0}) is None
    assert wd.check({"5m": 20.0, "1h": 7.0}) is None, \
        "no re-fire before the hysteresis re-arm"
    # Below half both: re-armed; next breach fires again.
    assert wd.check({"5m": 1.0, "1h": 1.0}) is None
    assert wd.check({"5m": 20.0, "1h": 7.0}) is not None
    assert len(wd.fired) == 2


# --------------------------------------------- router: stub-fleet tracing
def _traced_fleet(stubs):
    router = FleetRouter(
        {s.name: s.url for s in stubs},
        RouterConfig(health_timeout_s=2.0, fail_after=1,
                     request_timeout_s=5.0, fleet_brownout=False,
                     trace_sample_rate=1.0, slo_ms=10_000.0))
    router.check_replicas()
    return router


def test_router_trace_spans_and_header_propagation(fleet3):
    stubs, _ = fleet3
    router = _traced_fleet(stubs)
    server = RouterHTTPServer(router, port=0).start()
    try:
        status, headers, _ = _post(f"{server.url}/v1/disparity", b"px")
        assert status == 200
        tid = headers.get("X-Trace-Id")
        assert tid, "sampled request must echo its trace id"
        # The forwarded hop carried the context header naming the SAME
        # trace id (the replica-side adoption hook).
        fwd = [h for s in stubs for h in s.stateless_headers]
        assert len(fwd) == 1
        ctx = decode_traceparent(fwd[0].get("traceparent"))
        assert ctx is not None and ctx.trace_id == tid
        # The router's own ring has the route.request tree.
        status, _, body = _get(f"{server.url}/debug/spans?trace={tid}")
        assert status == 200
        view = json.loads(body)
        names = [s["name"] for s in view["spans"]]
        assert "route.request" in names and "route.forward" in names
        assert "route.pick" in names and "route.respond" in names
        assert all(s["trace_id"] == tid for s in view["spans"])
        # The forward span's id is the replica-side parent.
        fwd_span = next(s for s in view["spans"]
                        if s["name"] == "route.forward")
        assert ctx.parent_span_id == fwd_span["span_id"]
    finally:
        server.shutdown()
        router.stop()


def test_router_rate_zero_keeps_forwarding_untraced(fleet3):
    stubs, router = fleet3          # fleet3 router has sample rate 0
    server = RouterHTTPServer(router, port=0).start()
    try:
        status, headers, _ = _post(f"{server.url}/v1/disparity", b"px")
        assert status == 200
        assert "X-Trace-Id" not in headers
        fwd = [h for s in stubs for h in s.stateless_headers]
        assert all("traceparent" not in
                   {k.lower() for k in h} for h in fwd)
        assert router.tracer.stats()["traces_started"] == 0
    finally:
        server.shutdown()


def test_failover_retry_is_two_forward_children_one_trace(fleet3):
    """ISSUE acceptance: a transport failover mid-request shows up as
    TWO route.forward children (first with error=transport) under ONE
    trace id."""
    stubs, _ = fleet3
    router = _traced_fleet(stubs)
    server = RouterHTTPServer(router, port=0).start()
    try:
        stubs[0].kill()             # dead but still in rotation: the
        tid_with_retry = None       # next pick of s0 fails over inline
        for _ in range(12):
            status, headers, _ = _post(f"{server.url}/v1/disparity",
                                       b"px")
            assert status == 200
            tid = headers["X-Trace-Id"]
            spans = [s.to_dict() for s in router.tracer.spans()
                     if s.trace_id == tid]
            fwd = [s for s in spans if s["name"] == "route.forward"]
            if len(fwd) >= 2:
                tid_with_retry = tid
                errors = [s["attrs"].get("error") for s in fwd]
                assert "transport" in errors
                ok = [s for s in fwd
                      if s["attrs"].get("status") == 200]
                assert len(ok) == 1
                root = [s for s in spans
                        if s["name"] == "route.request"]
                assert len(root) == 1
                assert all(s["trace_id"] == tid for s in fwd + root)
                break
        assert tid_with_retry is not None, \
            "12 requests over a 1/3-dead fleet must hit the dead " \
            "replica at least once"
    finally:
        server.shutdown()
        router.stop()


def test_router_error_paths_carry_trace_id_and_burn_budget(fleet3):
    stubs, _ = fleet3
    router = _traced_fleet(stubs)
    server = RouterHTTPServer(router, port=0).start()
    try:
        router.slo_tick()           # baseline snapshot to burn against
        # 410 session_lost: place a session, kill its replica, probe it
        # out of rotation.
        status, headers, _ = _post(f"{server.url}/v1/stream/cam-x", b"f")
        assert status == 200 and headers.get("X-Trace-Id")
        owner = next(s for s in stubs if "cam-x" in s.sessions)
        owner.kill()
        router.check_replicas()
        router.check_replicas()
        status, headers, body = _post(f"{server.url}/v1/stream/cam-x",
                                      b"f")
        assert status == 410
        assert json.loads(body)["error"] == "session_lost"
        assert headers.get("X-Trace-Id"), \
            "typed router errors must stay traceable"
        errors_after_410 = router.slo_errors.value
        assert errors_after_410 >= 1
        # 503 no_replicas_ready.
        for s in stubs:
            if s is not owner:
                s.kill()
        router.check_replicas()
        router.check_replicas()
        status, headers, body = _post(f"{server.url}/v1/disparity", b"x")
        assert status == 503
        assert json.loads(body)["error"] == "no_replicas_ready"
        assert headers.get("X-Trace-Id")
        assert router.slo_errors.value > errors_after_410
        # The SLO sampler folds the typed errors into the bad totals.
        burns = router.slo_tick()
        assert burns["5m"] > 0.0
    finally:
        server.shutdown()
        router.stop()


def test_router_metrics_fleet_federates_stub_series(fleet3):
    stubs, _ = fleet3
    router = _traced_fleet(stubs)
    server = RouterHTTPServer(router, port=0).start()
    try:
        assert router.federator.scrape_once() == {
            s.name: True for s in stubs}
        status, headers, body = _get(f"{server.url}/metrics/fleet")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        text = body.decode()
        # Router's own series, unlabelled (the router IS this target)…
        assert "fleet_replicas_ready" in text
        # …every stub's series with replica= injected, one HELP each…
        for s in stubs:
            assert f'fleet_federation_up{{replica="{s.name}"}} 1' in text
            assert (f'stub_requests_total{{replica="{s.name}",'
                    f'stub="{s.name}"}} 0') in text
        assert text.count("# HELP stub_requests_total") == 1
        # …and a mid-scrape death degrades to a stale marker without
        # stalling the endpoint.
        stubs[1].kill()
        router.federator.scrape_once()
        t0 = time.monotonic()
        status, _, body = _get(f"{server.url}/metrics/fleet")
        assert status == 200 and time.monotonic() - t0 < 1.0
        assert (f'fleet_federation_up{{replica="{stubs[1].name}"}} 0'
                in body.decode())
    finally:
        server.shutdown()
        router.stop()


def test_router_federated_spans_merge_replica_ring(fleet3):
    stubs, _ = fleet3
    router = _traced_fleet(stubs)
    server = RouterHTTPServer(router, port=0).start()
    try:
        status, headers, _ = _post(f"{server.url}/v1/disparity", b"px")
        tid = headers["X-Trace-Id"]
        # Script the serving-side half of the trace on every stub (the
        # real-engine merge is test_e2e below); the federated view must
        # pull the owning replica's spans and tag provenance.
        handler = next(s for s in stubs if s.stateless_headers)
        ctx = decode_traceparent(
            handler.stateless_headers[0]["traceparent"])
        handler.spans[tid] = [{
            "name": "serve.request", "trace_id": tid,
            "span_id": "aa" * 4, "parent_id": ctx.parent_span_id,
            "start_us": time.time() * 1e6, "duration_us": 42.0,
            "attrs": {}}]
        status, _, body = _get(f"{server.url}/debug/spans?trace={tid}")
        view = json.loads(body)
        procs = {s["process"] for s in view["spans"]}
        assert "router" in procs and handler.name in procs
        assert view["sources"][handler.name] == 1
        served = next(s for s in view["spans"]
                      if s["name"] == "serve.request")
        fwd_ids = {s["span_id"] for s in view["spans"]
                   if s["name"] == "route.forward"}
        assert served["parent_id"] in fwd_ids, \
            "replica subtree must stitch under the forward span"
    finally:
        server.shutdown()
        router.stop()


def test_fleet_status_and_replica_probe_stats(fleet3):
    """Satellite 2: /fleet entries expose probe_latency_ms (EWMA),
    last_state_change_ts, and the consecutive-failure count."""
    stubs, _ = fleet3
    router = _traced_fleet(stubs)
    router.check_replicas()
    st = router.fleet_status()
    assert st["slo"]["availability_objective"] == 0.999
    assert "5m" in st["slo"]["burn_rates"]
    assert st["federation"]["poll_s"] == 5.0
    for name, entry in st["replicas"].items():
        assert entry["probe_latency_ms"] is not None
        assert entry["probe_latency_ms"] >= 0.0
        assert entry["last_state_change_ts"] is not None
        assert entry["consecutive_failures"] == 0
    before = {n: e["last_state_change_ts"]
              for n, e in st["replicas"].items()}
    stubs[0].kill()
    time.sleep(0.05)
    router.check_replicas()
    entry = router.fleet_status()["replicas"][stubs[0].name]
    assert entry["consecutive_failures"] >= 1
    assert entry["last_state_change_ts"] > before[stubs[0].name]
    router.stop()


def test_watchdog_triggers_coordinated_fleet_dump(fleet3, tmp_path):
    """The full detector loop: synthesized burn → watchdog trip → router
    bundle + every replica POSTed /debug/flightrecorder + one manifest
    linking them under the trigger trace id."""
    stubs, _ = fleet3
    router = FleetRouter(
        {s.name: s.url for s in stubs},
        RouterConfig(health_timeout_s=2.0, fail_after=1,
                     request_timeout_s=5.0, fleet_brownout=False,
                     trace_sample_rate=1.0,
                     flight_recorder_dir=str(tmp_path)))
    router.check_replicas()
    try:
        rec = router.slo_watchdog.check({"5m": 100.0, "1h": 100.0})
        assert rec is not None
        manifest = rec["fleet_dump"]
        assert manifest["trigger_trace_id"] == rec["trigger_trace_id"]
        assert manifest["router_bundle"] is not None
        assert set(manifest["replicas"]) == {s.name for s in stubs}
        for s in stubs:
            assert s.flightrecorder_dumps == 1
            assert manifest["replicas"][s.name]["status"] == "dumped"
        with open(manifest["manifest_path"]) as f:
            on_disk = json.load(f)
        assert on_disk["trigger_trace_id"] == rec["trigger_trace_id"]
        assert router.anomalies.value == 1
        assert router.fleet_status()["fleet_dumps"] == 1
    finally:
        router.stop()


# ------------------------------------------------- real engine end-to-end
@pytest.mark.slow
def test_e2e_one_trace_id_across_router_and_real_engine(tiny_model):
    """ISSUE acceptance (e2e): rate-1.0 router in front of a REAL
    engine replica — the response's X-Trace-Id resolves through the
    router's federated /debug/spans to a merged timeline whose
    serve.request (replica process) is a child of the router's
    route.forward span."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    rng = np.random.default_rng(3)
    left = rng.integers(0, 255, (48, 64, 3), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, left=left, right=np.roll(left, -3, axis=1))
    payload = buf.getvalue()
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=1, batch_sizes=(1,),
                                    iters=1))
    server = StereoHTTPServer(svc, port=0).start()
    router = FleetRouter(
        {"r0": server.url},
        RouterConfig(health_timeout_s=5.0, fleet_brownout=False,
                     trace_sample_rate=1.0))
    router.check_replicas()
    rserver = RouterHTTPServer(router, port=0).start()
    try:
        status, headers, _ = _post(
            f"{rserver.url}/v1/disparity", payload,
            {"Content-Type": "application/x-npz"}, timeout=300)
        assert status == 200
        tid = headers["X-Trace-Id"]
        assert tid
        # Replica side: the engine ran at sample rate 0 but ADOPTED the
        # router's context — its own /debug/spans knows the trace id.
        status, _, body = _get(
            f"{server.url}/debug/spans?trace={tid}", timeout=30)
        replica_view = json.loads(body)
        assert any(s["name"] == "serve.request"
                   for s in replica_view["spans"])
        # Router side: the federated endpoint merges both processes
        # into one timeline under the one id.
        status, _, body = _get(
            f"{rserver.url}/debug/spans?trace={tid}", timeout=30)
        view = json.loads(body)
        by_proc = {}
        for s in view["spans"]:
            by_proc.setdefault(s["process"], []).append(s)
        assert "router" in by_proc and "r0" in by_proc
        serve_root = next(s for s in by_proc["r0"]
                          if s["name"] == "serve.request")
        fwd = next(s for s in by_proc["router"]
                   if s["name"] == "route.forward")
        assert serve_root["parent_id"] == fwd["span_id"]
        assert serve_root["trace_id"] == fwd["trace_id"] == tid
        # Timeline ordering: merged spans sort by wall-clock start.
        starts = [s["start_us"] for s in view["spans"]]
        assert starts == sorted(starts)
    finally:
        rserver.shutdown()
        router.stop()
        server.shutdown()
        svc.close()
