"""Offline accuracy-gate evidence: end-to-end evaluation parity vs the
ACTUAL reference evaluation stack.

The environment has zero network egress (BASELINE.md), so the published
checkpoint zoo and real benchmark datasets cannot be fetched.  This is the
strongest accuracy evidence constructible offline, and it exercises every
stage the real Middlebury-H gate would:

    reference:  stereo_datasets readers -> InputPadder -> RAFTStereo(torch,
                CPU) -> unpad -> evaluate_stereo.validate_* metrics
    ours:       data.datasets readers -> ops.padding -> RAFTStereo(jax) via
                io.torch_import -> eval.validate_* metrics

Both run on byte-identical mini-benchmark trees (tests/golden_data.py, the
exact on-disk layouts of ETH3D / KITTI / FlyingThings / Middlebury) with
byte-identical weights, and the resulting EPE / D1 numbers are compared.
The reference validators are the real ones imported from
/root/reference/evaluate_stereo.py (``.cuda()`` patched to identity — the
only change needed to run them on CPU).

When network exists, scripts/download_models.sh + download_datasets.sh make
the same comparison runnable on the real published checkpoints/datasets.
"""

import os
import sys
from types import SimpleNamespace

import numpy as np
import pytest

torch = pytest.importorskip("torch")

pytestmark = pytest.mark.slow

REFERENCE = "/root/reference"
ITERS = 8


@pytest.fixture(scope="module")
def bench_root(tmp_path_factory):
    from golden_data import make_all_benchmarks

    root = str(tmp_path_factory.mktemp("bench"))
    make_all_benchmarks(root)
    return root


def _reference_on_path():
    """Make /root/reference importable (core.* and flat module names)."""
    for p in (REFERENCE, os.path.join(REFERENCE, "core")):
        if p not in sys.path:
            sys.path.insert(0, p)


def _patch_cuda_identity(monkeypatch):
    """The only CPU-hostile thing in the reference validators is .cuda()."""
    monkeypatch.setattr(torch.Tensor, "cuda",
                        lambda self, *a, **k: self, raising=True)


@pytest.fixture(scope="module")
def ref_model_and_pth(tmp_path_factory):
    """The actual reference model (default published architecture), seeded
    random weights, eval mode, plus its state_dict saved as .pth."""
    _reference_on_path()
    from core.raft_stereo import RAFTStereo as TorchRAFTStereo

    args = SimpleNamespace(hidden_dims=[128, 128, 128],
                           corr_implementation="reg", shared_backbone=False,
                           corr_levels=4, corr_radius=4, n_downsample=2,
                           context_norm="batch", slow_fast_gru=False,
                           n_gru_layers=3, mixed_precision=False)
    torch.manual_seed(0)
    model = TorchRAFTStereo(args)
    model.eval()
    pth = str(tmp_path_factory.mktemp("weights") / "ref.pth")
    torch.save(model.state_dict(), pth)
    return model, pth


def _stub_missing_reference_deps():
    """The environment lacks scikit-image and torchvision; the reference
    imports them only inside its augmentor module (core/utils/augmentor.py:
    7,15), whose classes the validators never instantiate (aug_params={} →
    no augmentor, stereo_datasets.py:26-30).  Empty stubs make its
    evaluation stack importable."""
    import types

    def module(name, **attrs):
        if name in sys.modules:
            return sys.modules[name]
        m = types.ModuleType(name)
        for k, v in attrs.items():
            setattr(m, k, v)
        sys.modules[name] = m
        return m

    fn = module("torchvision.transforms.functional")
    module("torchvision.transforms", ColorJitter=object, Compose=object,
           functional=fn)
    module("torchvision")
    module("skimage.color")
    module("skimage.io")
    sk = module("skimage")
    sk.color = sys.modules["skimage.color"]
    sk.io = sys.modules["skimage.io"]


def _run_reference_validators(bench_root, model, monkeypatch):
    _stub_missing_reference_deps()
    import evaluate_stereo as es

    _patch_cuda_identity(monkeypatch)
    monkeypatch.chdir(bench_root)  # reference roots are relative 'datasets/…'
    res = {}
    res.update(es.validate_eth3d(model, iters=ITERS))
    res.update(es.validate_kitti(model, iters=ITERS))
    res.update(es.validate_things(model, iters=ITERS))
    res.update(es.validate_middlebury(model, iters=ITERS, split="H"))
    return res


def _run_our_validators(bench_root, pth):
    from raft_stereo_tpu.eval import validate as V
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.io.torch_import import import_torch_checkpoint

    cfg, variables = import_torch_checkpoint(pth)
    runner = InferenceRunner(cfg, variables, iters=ITERS)
    d = os.path.join(bench_root, "datasets")
    res = {}
    res.update(V.validate_eth3d(runner, root=os.path.join(d, "ETH3D")))
    res.update(V.validate_kitti(runner, root=os.path.join(d, "KITTI")))
    res.update(V.validate_things(runner, root=d))
    res.update(V.validate_middlebury(runner,
                                     root=os.path.join(d, "Middlebury"),
                                     split="H"))
    return res


def test_eval_parity_all_benchmarks(bench_root, ref_model_and_pth,
                                    monkeypatch):
    model, pth = ref_model_and_pth
    ref = _run_reference_validators(bench_root, model, monkeypatch)
    ours = _run_our_validators(bench_root, pth)

    print(f"\nreference: { {k: round(v, 5) for k, v in sorted(ref.items())} }")
    print(f"ours:      { {k: round(v, 5) for k, v in sorted(ours.items())} }")
    assert set(ref) == set(ours)
    for k in sorted(ref):
        if k.endswith("-epe"):
            # per-pixel forward parity is <5e-3 (test_torch_parity); the
            # image-mean EPE through the full data/pad/metric pipeline must
            # agree far inside that
            assert abs(ours[k] - ref[k]) < 2e-3 + 1e-3 * abs(ref[k]), (
                k, ref[k], ours[k])
        else:  # d1 in percent; only threshold-straddling pixels can differ
            assert abs(ours[k] - ref[k]) < 0.5, (k, ref[k], ours[k])


def test_eval_parity_realtime_architecture(tmp_path_factory, monkeypatch):
    """The published REALTIME layout (shared backbone, n_downsample=3,
    2 GRU levels, slow-fast) through both full evaluation stacks — the key
    layout VERDICT round 1 flagged as never exercised end-to-end.  Wider
    frames than the module fixture: at 1/8 resolution the reference's
    4-level pyramid needs W/8 >= 2^4 disparity bins."""
    from golden_data import make_kitti

    _reference_on_path()
    from core.raft_stereo import RAFTStereo as TorchRAFTStereo

    root = str(tmp_path_factory.mktemp("bench_rt"))
    make_kitti(os.path.join(root, "datasets", "KITTI"),
               np.random.default_rng(5), n=2, hw=(64, 160))

    args = SimpleNamespace(hidden_dims=[128, 128, 128],
                           corr_implementation="reg", shared_backbone=True,
                           corr_levels=4, corr_radius=4, n_downsample=3,
                           context_norm="batch", slow_fast_gru=True,
                           n_gru_layers=2, mixed_precision=False)
    torch.manual_seed(3)
    model = TorchRAFTStereo(args)
    model.eval()
    pth = str(tmp_path_factory.mktemp("weights_rt") / "rt.pth")
    torch.save(model.state_dict(), pth)

    _stub_missing_reference_deps()
    import evaluate_stereo as es
    _patch_cuda_identity(monkeypatch)
    monkeypatch.chdir(root)
    ref = es.validate_kitti(model, iters=ITERS)

    from raft_stereo_tpu.eval import validate as V
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.io.torch_import import import_torch_checkpoint

    cfg, variables = import_torch_checkpoint(pth, slow_fast_gru=True)
    assert cfg.shared_backbone and cfg.n_downsample == 3
    assert cfg.n_gru_layers == 2
    runner = InferenceRunner(cfg, variables, iters=ITERS)
    ours = V.validate_kitti(runner,
                            root=os.path.join(root, "datasets", "KITTI"))

    assert abs(ours["kitti-epe"] - ref["kitti-epe"]) < (
        2e-3 + 1e-3 * abs(ref["kitti-epe"])), (ref, ours)
    assert abs(ours["kitti-d1"] - ref["kitti-d1"]) < 0.5, (ref, ours)


def test_eval_parity_hard_benchmark_regime(tmp_path_factory,
                                           ref_model_and_pth, monkeypatch):
    """Round 5: the same byte-identical four-validator parity on HARD
    layered scenes — true occlusions in each benchmark's native encoding
    (computed Middlebury nocc masks, ETH3D +inf at occlusions, KITTI occ
    -split sparse GT), disparities deep into the metric domain.  The easy
    -tree test above proves the pipelines agree; this proves they agree
    exactly where the masks MATTER (occluded/invalid pixels are a double
    -digit fraction of every image here)."""
    from golden_data import (make_eth3d, make_kitti, make_middlebury,
                             make_things)

    root = str(tmp_path_factory.mktemp("bench_hard"))
    rng = np.random.default_rng(77)
    d = os.path.join(root, "datasets")
    hw = (96, 224)
    make_eth3d(os.path.join(d, "ETH3D"), rng, hw=hw, hard=True)
    make_kitti(os.path.join(d, "KITTI"), rng, hw=hw, hard=True)
    make_things(d, rng, hw=hw, hard=True)
    make_middlebury(os.path.join(d, "Middlebury"), rng, hw=hw, hard=True)

    model, pth = ref_model_and_pth
    ref = _run_reference_validators(root, model, monkeypatch)
    ours = _run_our_validators(root, pth)

    print(f"\nreference: { {k: round(v, 5) for k, v in sorted(ref.items())} }")
    print(f"ours:      { {k: round(v, 5) for k, v in sorted(ours.items())} }")
    assert set(ref) == set(ours)
    for k in sorted(ref):
        if k.endswith("-epe"):
            assert abs(ours[k] - ref[k]) < 2e-3 + 1e-3 * abs(ref[k]), (
                k, ref[k], ours[k])
        else:
            assert abs(ours[k] - ref[k]) < 0.5, (k, ref[k], ours[k])
