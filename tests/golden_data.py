"""Synthetic-but-realistic benchmark mini-trees for offline eval parity.

The environment has no network egress (see BASELINE.md), so the published
checkpoints and benchmark datasets cannot be fetched.  This module builds
miniature versions of the four evaluation benchmarks in the EXACT on-disk
layouts the reference globs (reference: core/stereo_datasets.py:185-274),
with textured stereo pairs where the right view is a true horizontal warp of
the left by a known disparity field — so both the reference's
``evaluate_stereo.py`` validators and ours can run end-to-end on identical
bytes and their EPE/D1 numbers can be compared exactly.

Images are multi-scale filtered noise (not flat randomness) so feature
encoders see realistic local structure; disparity is a smooth ramp plus
foreground rectangles (depth discontinuities), with each benchmark's native
invalid-pixel encoding (inf PFM values, zero KITTI png, Middlebury nocc
mask).
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image

from raft_stereo_tpu.data import frame_utils


def textured_image(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    """Multi-octave smooth noise -> (H, W, 3) uint8 with local structure."""
    acc = np.zeros((h, w), np.float32)
    for period in (4, 8, 16, 32):
        gh, gw = h // period + 2, w // period + 2
        grid = rng.standard_normal((gh, gw)).astype(np.float32)
        up = Image.fromarray(grid).resize((w, h), Image.BILINEAR)
        acc += period * np.asarray(up, np.float32)
    acc = (acc - acc.min()) / (acc.max() - acc.min() + 1e-9)
    r = (acc * 255).astype(np.uint8)
    g = np.roll(r, 3, axis=1)
    b = np.roll(r, 3, axis=0)
    return np.stack([r, g, b], axis=-1)


def disparity_field(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    """Smooth ramp + foreground rectangles, positive, max ~12 px."""
    y, x = np.mgrid[0:h, 0:w].astype(np.float32)
    disp = 3.0 + 4.0 * x / w + 1.5 * np.sin(2 * np.pi * y / h)
    for _ in range(2):
        y0 = int(rng.integers(0, h // 2))
        x0 = int(rng.integers(0, w // 2))
        hh = int(rng.integers(h // 6, h // 3))
        ww = int(rng.integers(w // 6, w // 3))
        disp[y0:y0 + hh, x0:x0 + ww] += float(rng.uniform(2.0, 5.0))
    return disp.astype(np.float32)


def layered_scene(rng: np.random.Generator, h: int, w: int,
                  d_max: float | None = None, n_layers: int | None = None,
                  p_textureless: float = 0.25,
                  d_ceiling: float | None = None):
    """Geometrically exact layered stereo scene in the BENCHMARK disparity
    regime — the round-5 hardening of ``disparity_field``/``warp_right``.

    The reference's metrics are defined over |d| < 192
    (reference: evaluate_stereo.py:133-135) and its training data (SceneFlow)
    is rendered geometry with depth discontinuities, true occlusions, and
    textureless surfaces; the old generator topped out near 12 px, two
    orders of magnitude inside that regime.  This one draws:

    * a background PLANE plus ``n_layers`` foreground planar layers with
      elliptical/rectangular supports, disparities log-uniform up to a
      per-scene ceiling in (0.35, 1.0] * ``d_max`` (so the corpus covers
      the whole range, not just its top);
    * each view rendered INDEPENDENTLY by per-pixel z-buffer (near = larger
      disparity wins).  A planar layer maps right pixel ``xr`` to the left
      /canvas abscissa ``xl = (xr + a + c*y/h) / (1 - b/w)`` (closed form —
      no fixed-point iteration, no resampling error), so the right view is
      TRUE alternate-viewpoint geometry, not a backward warp of the left:
      occluded background is revealed, foreground edges occlude;
    * a TRUE occlusion mask by left-right consistency of the two visible
      surfaces: left pixel (y, x) with visible disparity d is occluded iff
      its match ``x - d`` falls outside the right frame or the right view's
      visible surface there is nearer by > 1 px (exact for planar layers:
      the right-view disparity of the SAME surface is linear in xr, so the
      per-row linear interpolation reproduces it perfectly away from
      layer boundaries);
    * textureless content: each foreground layer is flat (+tiny noise) with
      probability ``p_textureless``, and one blurred-flat patch is carved
      into the background texture.

    Textures live on a canvas of width ``w + ceil(d_ceiling) + 2`` so right
    -view sampling at ``x + d`` never clamps (the old generator's
    BORDER_REPLICATE streaks).  Returns ``(left u8 (H,W,3), right u8
    (H,W,3), disp f32 (H,W) positive left-view GT — dense, occluded pixels
    INCLUDED, exactly like rendered SceneFlow GT — and occ bool (H,W))``.
    """
    if d_max is None:
        # keep the geometry plausible on tiny parity trees (w=90 -> ~31 px)
        d_max = min(190.0, 0.35 * w)
    if n_layers is None:
        n_layers = int(rng.integers(4, 9))
    if d_ceiling is None:
        d_ceiling = float(rng.uniform(0.35, 1.0)) * d_max
    # margin absorbs plane slopes (<= 0.06*d_ceiling each of b, c)
    w_ext = w + int(np.ceil(1.15 * d_ceiling)) + 2
    yy = np.arange(h, dtype=np.float32)[:, None] / h          # (H,1)
    xr = np.arange(w, dtype=np.float32)[None, :]              # (1,W)
    xl_grid = np.arange(w, dtype=np.float32)[None, :]

    def plane_params(lo, hi, slope):
        a = float(rng.uniform(lo, hi))
        b = float(rng.uniform(-slope, slope))
        c = float(rng.uniform(-slope, slope))
        return a, b, c

    def flat_texture():
        base = rng.uniform(40, 215, size=3)
        tex = np.broadcast_to(base.astype(np.float32),
                              (h, w_ext, 3)).copy()
        tex += rng.standard_normal((h, w_ext, 3)).astype(np.float32) * 1.5
        return np.clip(tex, 0, 255)

    def support_mask():
        """Rotated ellipse or rectangle on the canvas, area ~2-12%."""
        cy = rng.uniform(0.1 * h, 0.9 * h)
        cx = rng.uniform(0.05 * w_ext, 0.95 * w_ext)
        ry = rng.uniform(0.10 * h, 0.32 * h)
        rx = rng.uniform(0.06 * w_ext, 0.22 * w_ext)
        th = rng.uniform(0, np.pi)
        gy, gx = np.mgrid[0:h, 0:w_ext].astype(np.float32)
        u = (gx - cx) * np.cos(th) + (gy - cy) * np.sin(th)
        v = -(gx - cx) * np.sin(th) + (gy - cy) * np.cos(th)
        if rng.random() < 0.5:
            return (u / rx) ** 2 + (v / ry) ** 2 <= 1.0
        return (np.abs(u) <= rx) & (np.abs(v) <= ry)

    # --- layers: (a, b, c) plane in left/canvas coords, mask, texture ----
    layers = []
    bg_d0 = float(rng.uniform(1.0, 0.25 * d_ceiling))
    # |c| < bg_d0 - 0.5 keeps the background disparity positive everywhere,
    # so the background plane covers every right-view pixel (no holes)
    c_cap = min(0.1 * d_ceiling, max(bg_d0 - 0.5, 0.0))
    a, b, c = bg_d0, float(rng.uniform(0.0, 0.2 * d_ceiling)), \
        float(rng.uniform(-c_cap, c_cap))
    bg_tex = textured_image(rng, h, w_ext).astype(np.float32)
    # carve one textureless patch into the background
    py0, px0 = int(rng.integers(0, h // 2)), int(rng.integers(0, w_ext // 2))
    ph, pw = h // 4, w_ext // 5
    bg_tex[py0:py0 + ph, px0:px0 + pw] = \
        bg_tex[py0:py0 + ph, px0:px0 + pw].mean(axis=(0, 1), keepdims=True)
    layers.append((a, b, c, np.ones((h, w_ext), bool), bg_tex))
    lo = max(bg_d0 + 0.15 * d_ceiling, 0.2 * d_ceiling)
    for k in range(n_layers):
        # log-uniform base so near AND far layers both appear; the first
        # layer sits AT the ceiling so every scene exercises its full range
        base = d_ceiling if k == 0 else float(
            np.exp(rng.uniform(np.log(lo), np.log(d_ceiling))))
        slope = 0.06 * d_ceiling
        af = base
        bf = float(rng.uniform(-slope, slope))
        cf = float(rng.uniform(-slope, slope))
        tex = (flat_texture() if rng.random() < p_textureless
               else textured_image(rng, h, w_ext).astype(np.float32))
        layers.append((af, bf, cf, support_mask(), tex))

    def lerp_row(img, xs):
        """Per-row linear interpolation of (H, W_ext[, C]) at float xs
        (H, W); xs guaranteed in [0, w_ext-1]."""
        x0 = np.clip(np.floor(xs).astype(np.int64), 0, w_ext - 2)
        fr = (xs - x0)[..., None] if img.ndim == 3 else (xs - x0)
        g0 = np.take_along_axis(
            img, x0[..., None] if img.ndim == 3 else x0, axis=1)
        g1 = np.take_along_axis(
            img, (x0 + 1)[..., None] if img.ndim == 3 else x0 + 1, axis=1)
        return g0 * (1 - fr) + g1 * fr

    # --- left view: z-buffer in canvas coords, crop to [0, w) -----------
    left = np.zeros((h, w, 3), np.float32)
    disp_l = np.full((h, w), -np.inf, np.float32)
    for a, b, c, mask, tex in layers:
        d = a + b * xl_grid / w + c * yy                       # (H,W)
        cover = mask[:, :w] & (d > disp_l)
        disp_l = np.where(cover, d, disp_l)
        left = np.where(cover[..., None], tex[:, :w], left)

    # --- right view: closed-form inverse warp per layer, z-buffer -------
    right = np.zeros((h, w, 3), np.float32)
    disp_r = np.full((h, w), -np.inf, np.float32)
    for a, b, c, mask, tex in layers:
        denom = 1.0 - b / w
        xl = (xr + a + c * yy) / denom                         # (H,W)
        inside = (xl >= 0) & (xl <= w_ext - 1)
        xl_s = np.clip(xl, 0, w_ext - 1)
        cover = inside & (lerp_row(mask.astype(np.float32), xl_s) > 0.5)
        d = a + b * xl / w + c * yy
        take = cover & (d > disp_r)
        disp_r = np.where(take, d, disp_r)
        right = np.where(take[..., None], lerp_row(tex, xl_s), right)

    # --- true occlusion: left-right consistency of visible surfaces -----
    xmatch = xl_grid - disp_l                                  # (H,W)
    off_frame = xmatch < -0.5
    xm = np.clip(xmatch, 0, w - 1)
    x0 = np.clip(np.floor(xm).astype(np.int64), 0, w - 2)
    fr = xm - x0
    # guard -inf (a right pixel no layer covered) against 0*inf = nan
    disp_r_f = np.nan_to_num(disp_r, neginf=-1e9)
    dr0 = np.take_along_axis(disp_r_f, x0, axis=1)
    dr1 = np.take_along_axis(disp_r_f, x0 + 1, axis=1)
    dr_at_match = dr0 * (1 - fr) + dr1 * fr
    occ = off_frame | (dr_at_match > disp_l + 1.01)

    return (np.clip(left, 0, 255).astype(np.uint8),
            np.clip(right, 0, 255).astype(np.uint8),
            disp_l.astype(np.float32), occ)


def hard_pair(rng, h, w, d_max: float | None = None):
    """(left, right, disp, occ) in the benchmark disparity regime."""
    return layered_scene(rng, h, w, d_max=d_max)


def warp_right(left: np.ndarray, disp: np.ndarray) -> np.ndarray:
    """right[y, x] = left[y, x + disp[y, x]] per-row linear interpolation —
    the stereo geometry (matching left pixel sits ``disp`` to the RIGHT of
    the right-image pixel)."""
    h, w, _ = left.shape
    xs = np.arange(w, dtype=np.float32)
    out = np.empty_like(left)
    for yy in range(h):
        src = xs + disp[yy]
        for c in range(3):
            out[yy, :, c] = np.interp(src, xs, left[yy, :, c].astype(np.float32))
    return out.astype(np.uint8)


def _pair(rng, h, w):
    left = textured_image(rng, h, w)
    disp = disparity_field(rng, h, w)
    right = warp_right(left, disp)
    return left, right, disp


def make_eth3d(root: str, rng, n: int = 2, hw=(60, 90),
               hard: bool = False) -> None:
    """two_view_training/<scene>/im{0,1}.png + two_view_training_gt/<scene>/
    disp0GT.pfm; invalid pixels are +inf (reference: stereo_datasets.py:185-195,
    valid = disp < 512 via the non-tuple reader path).  ``hard=True`` draws
    benchmark-regime layered scenes; the real ETH3D laser GT is missing
    exactly where the scan could not see — occluded regions — so those are
    +inf along with a small random dropout."""
    h, w = hw
    for i in range(n):
        scene = os.path.join(root, "two_view_training", f"scene_{i}")
        gt = os.path.join(root, "two_view_training_gt", f"scene_{i}")
        os.makedirs(scene), os.makedirs(gt)
        if hard:
            left, right, disp, occ = hard_pair(rng, h, w)
            disp = disp.copy()
            disp[occ] = np.inf
        else:
            left, right, disp = _pair(rng, h, w)
            disp = disp.copy()
        Image.fromarray(left).save(os.path.join(scene, "im0.png"))
        Image.fromarray(right).save(os.path.join(scene, "im1.png"))
        disp[rng.random((h, w)) < 0.05] = np.inf  # ETH3D invalid encoding
        frame_utils.write_pfm(os.path.join(gt, "disp0GT.pfm"), disp)


def make_kitti(root: str, rng, n: int = 2, hw=(60, 90),
               hard: bool = False) -> None:
    """training/{image_2,image_3,disp_occ_0}/<id>_10.png; sparse 16-bit
    disparity/256, zero = invalid (reference: stereo_datasets.py:246-257,
    frame_utils.py:124-127).  ``hard=True``: benchmark-regime layered
    scenes; ``disp_occ_0`` semantics are kept — GT at occluded pixels is
    INCLUDED (that is what the real occ split means), sparsity comes from
    random LiDAR-style dropout."""
    h, w = hw
    for sub in ("image_2", "image_3", "disp_occ_0"):
        os.makedirs(os.path.join(root, "training", sub))
    for i in range(n):
        if hard:
            left, right, disp, _occ = hard_pair(rng, h, w)
        else:
            left, right, disp = _pair(rng, h, w)
        Image.fromarray(left).save(
            os.path.join(root, "training", "image_2", f"{i:06d}_10.png"))
        Image.fromarray(right).save(
            os.path.join(root, "training", "image_3", f"{i:06d}_10.png"))
        disp = disp.copy()
        disp[rng.random((h, w)) < 0.4] = 0.0  # sparse: ~60% coverage
        frame_utils.write_disp_kitti(
            os.path.join(root, "training", "disp_occ_0", f"{i:06d}_10.png"),
            disp)


def make_things(root: str, rng, n: int = 2, hw=(60, 90),
                dstype: str = "frames_finalpass", hard: bool = False) -> None:
    """FlyingThings3D/<dstype>/TEST/A/<seq>/left|right/0006.png +
    disparity pfm.  With fewer than 400 files the seed-1000 validation
    subset selects ALL of them in both frameworks
    (reference: stereo_datasets.py:145-149).  ``hard=True``: layered
    scenes; SceneFlow GT is rendered and therefore DENSE — occluded pixels
    keep their true disparity, exactly as the real PFMs encode it."""
    h, w = hw
    for i in range(n):
        seq = os.path.join(root, "FlyingThings3D", dstype, "TEST", "A",
                           f"{i:04d}")
        dseq = os.path.join(root, "FlyingThings3D", "disparity", "TEST", "A",
                            f"{i:04d}", "left")
        os.makedirs(os.path.join(seq, "left"))
        os.makedirs(os.path.join(seq, "right"))
        os.makedirs(dseq)
        if hard:
            left, right, disp, _occ = hard_pair(rng, h, w)
        else:
            left, right, disp = _pair(rng, h, w)
        Image.fromarray(left).save(os.path.join(seq, "left", "0006.png"))
        Image.fromarray(right).save(os.path.join(seq, "right", "0006.png"))
        frame_utils.write_pfm(os.path.join(dseq, "0006.pfm"), disp)


def make_middlebury(root: str, rng, n: int = 2, hw=(60, 90),
                    split: str = "H", hard: bool = False) -> None:
    """MiddEval3/training<split>/<scene>/{im0,im1,disp0GT.pfm,mask0nocc.png}
    + the trainingF listing and official_train.txt filter the reference
    applies (reference: stereo_datasets.py:260-274); unknown GT is +inf,
    nocc mask 255 = non-occluded, 128 = occluded.  ``hard=True``: layered
    scenes with the nocc mask derived from the TRUE forward-warp occlusion
    (the real MiddEval3 masks encode exactly this visibility)."""
    h, w = hw
    names = []
    for i in range(n):
        name = f"Scene{i}"
        names.append(name)
        scene = os.path.join(root, "MiddEval3", f"training{split}", name)
        os.makedirs(scene)
        # the reference enumerates trainingF to list scene names
        os.makedirs(os.path.join(root, "MiddEval3", "trainingF", name),
                    exist_ok=True)
        if hard:
            left, right, disp, occ = hard_pair(rng, h, w)
            mask = np.where(occ, 128, 255).astype(np.uint8)
        else:
            left, right, disp = _pair(rng, h, w)
            mask = np.where(rng.random((h, w)) < 0.2, 128,
                            255).astype(np.uint8)
        Image.fromarray(left).save(os.path.join(scene, "im0.png"))
        Image.fromarray(right).save(os.path.join(scene, "im1.png"))
        disp = disp.copy()
        disp[rng.random((h, w)) < 0.04] = np.inf  # unknown GT
        frame_utils.write_pfm(os.path.join(scene, "disp0GT.pfm"), disp)
        Image.fromarray(mask).save(os.path.join(scene, "mask0nocc.png"))
    with open(os.path.join(root, "MiddEval3", "official_train.txt"),
              "w") as f:
        f.write("\n".join(names) + "\n")


def make_all_benchmarks(datasets_root: str, seed: int = 7) -> str:
    """Build all four mini-benchmarks under ``datasets_root`` (the directory
    the reference's relative default roots resolve against when it is the
    CWD).  Returns ``datasets_root``."""
    rng = np.random.default_rng(seed)
    make_eth3d(os.path.join(datasets_root, "datasets", "ETH3D"), rng)
    make_kitti(os.path.join(datasets_root, "datasets", "KITTI"), rng)
    make_things(os.path.join(datasets_root, "datasets"), rng)
    make_middlebury(os.path.join(datasets_root, "datasets", "Middlebury"),
                    rng)
    return datasets_root
