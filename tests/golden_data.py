"""Synthetic-but-realistic benchmark mini-trees for offline eval parity.

The environment has no network egress (see BASELINE.md), so the published
checkpoints and benchmark datasets cannot be fetched.  This module builds
miniature versions of the four evaluation benchmarks in the EXACT on-disk
layouts the reference globs (reference: core/stereo_datasets.py:185-274),
with textured stereo pairs where the right view is a true horizontal warp of
the left by a known disparity field — so both the reference's
``evaluate_stereo.py`` validators and ours can run end-to-end on identical
bytes and their EPE/D1 numbers can be compared exactly.

Images are multi-scale filtered noise (not flat randomness) so feature
encoders see realistic local structure; disparity is a smooth ramp plus
foreground rectangles (depth discontinuities), with each benchmark's native
invalid-pixel encoding (inf PFM values, zero KITTI png, Middlebury nocc
mask).
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image

from raft_stereo_tpu.data import frame_utils


def textured_image(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    """Multi-octave smooth noise -> (H, W, 3) uint8 with local structure."""
    acc = np.zeros((h, w), np.float32)
    for period in (4, 8, 16, 32):
        gh, gw = h // period + 2, w // period + 2
        grid = rng.standard_normal((gh, gw)).astype(np.float32)
        up = Image.fromarray(grid).resize((w, h), Image.BILINEAR)
        acc += period * np.asarray(up, np.float32)
    acc = (acc - acc.min()) / (acc.max() - acc.min() + 1e-9)
    r = (acc * 255).astype(np.uint8)
    g = np.roll(r, 3, axis=1)
    b = np.roll(r, 3, axis=0)
    return np.stack([r, g, b], axis=-1)


def disparity_field(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    """Smooth ramp + foreground rectangles, positive, max ~12 px."""
    y, x = np.mgrid[0:h, 0:w].astype(np.float32)
    disp = 3.0 + 4.0 * x / w + 1.5 * np.sin(2 * np.pi * y / h)
    for _ in range(2):
        y0 = int(rng.integers(0, h // 2))
        x0 = int(rng.integers(0, w // 2))
        hh = int(rng.integers(h // 6, h // 3))
        ww = int(rng.integers(w // 6, w // 3))
        disp[y0:y0 + hh, x0:x0 + ww] += float(rng.uniform(2.0, 5.0))
    return disp.astype(np.float32)


def warp_right(left: np.ndarray, disp: np.ndarray) -> np.ndarray:
    """right[y, x] = left[y, x + disp[y, x]] per-row linear interpolation —
    the stereo geometry (matching left pixel sits ``disp`` to the RIGHT of
    the right-image pixel)."""
    h, w, _ = left.shape
    xs = np.arange(w, dtype=np.float32)
    out = np.empty_like(left)
    for yy in range(h):
        src = xs + disp[yy]
        for c in range(3):
            out[yy, :, c] = np.interp(src, xs, left[yy, :, c].astype(np.float32))
    return out.astype(np.uint8)


def _pair(rng, h, w):
    left = textured_image(rng, h, w)
    disp = disparity_field(rng, h, w)
    right = warp_right(left, disp)
    return left, right, disp


def make_eth3d(root: str, rng, n: int = 2, hw=(60, 90)) -> None:
    """two_view_training/<scene>/im{0,1}.png + two_view_training_gt/<scene>/
    disp0GT.pfm; invalid pixels are +inf (reference: stereo_datasets.py:185-195,
    valid = disp < 512 via the non-tuple reader path)."""
    h, w = hw
    for i in range(n):
        scene = os.path.join(root, "two_view_training", f"scene_{i}")
        gt = os.path.join(root, "two_view_training_gt", f"scene_{i}")
        os.makedirs(scene), os.makedirs(gt)
        left, right, disp = _pair(rng, h, w)
        Image.fromarray(left).save(os.path.join(scene, "im0.png"))
        Image.fromarray(right).save(os.path.join(scene, "im1.png"))
        disp = disp.copy()
        disp[rng.random((h, w)) < 0.05] = np.inf  # ETH3D invalid encoding
        frame_utils.write_pfm(os.path.join(gt, "disp0GT.pfm"), disp)


def make_kitti(root: str, rng, n: int = 2, hw=(60, 90)) -> None:
    """training/{image_2,image_3,disp_occ_0}/<id>_10.png; sparse 16-bit
    disparity/256, zero = invalid (reference: stereo_datasets.py:246-257,
    frame_utils.py:124-127)."""
    h, w = hw
    for sub in ("image_2", "image_3", "disp_occ_0"):
        os.makedirs(os.path.join(root, "training", sub))
    for i in range(n):
        left, right, disp = _pair(rng, h, w)
        Image.fromarray(left).save(
            os.path.join(root, "training", "image_2", f"{i:06d}_10.png"))
        Image.fromarray(right).save(
            os.path.join(root, "training", "image_3", f"{i:06d}_10.png"))
        disp = disp.copy()
        disp[rng.random((h, w)) < 0.4] = 0.0  # sparse: ~60% coverage
        frame_utils.write_disp_kitti(
            os.path.join(root, "training", "disp_occ_0", f"{i:06d}_10.png"),
            disp)


def make_things(root: str, rng, n: int = 2, hw=(60, 90),
                dstype: str = "frames_finalpass") -> None:
    """FlyingThings3D/<dstype>/TEST/A/<seq>/left|right/0006.png +
    disparity pfm.  With fewer than 400 files the seed-1000 validation
    subset selects ALL of them in both frameworks
    (reference: stereo_datasets.py:145-149)."""
    h, w = hw
    for i in range(n):
        seq = os.path.join(root, "FlyingThings3D", dstype, "TEST", "A",
                           f"{i:04d}")
        dseq = os.path.join(root, "FlyingThings3D", "disparity", "TEST", "A",
                            f"{i:04d}", "left")
        os.makedirs(os.path.join(seq, "left"))
        os.makedirs(os.path.join(seq, "right"))
        os.makedirs(dseq)
        left, right, disp = _pair(rng, h, w)
        Image.fromarray(left).save(os.path.join(seq, "left", "0006.png"))
        Image.fromarray(right).save(os.path.join(seq, "right", "0006.png"))
        frame_utils.write_pfm(os.path.join(dseq, "0006.pfm"), disp)


def make_middlebury(root: str, rng, n: int = 2, hw=(60, 90),
                    split: str = "H") -> None:
    """MiddEval3/training<split>/<scene>/{im0,im1,disp0GT.pfm,mask0nocc.png}
    + the trainingF listing and official_train.txt filter the reference
    applies (reference: stereo_datasets.py:260-274); unknown GT is +inf,
    nocc mask 255 = non-occluded, 128 = occluded."""
    h, w = hw
    names = []
    for i in range(n):
        name = f"Scene{i}"
        names.append(name)
        scene = os.path.join(root, "MiddEval3", f"training{split}", name)
        os.makedirs(scene)
        # the reference enumerates trainingF to list scene names
        os.makedirs(os.path.join(root, "MiddEval3", "trainingF", name),
                    exist_ok=True)
        left, right, disp = _pair(rng, h, w)
        Image.fromarray(left).save(os.path.join(scene, "im0.png"))
        Image.fromarray(right).save(os.path.join(scene, "im1.png"))
        disp = disp.copy()
        disp[rng.random((h, w)) < 0.04] = np.inf  # unknown GT
        frame_utils.write_pfm(os.path.join(scene, "disp0GT.pfm"), disp)
        mask = np.where(rng.random((h, w)) < 0.2, 128, 255).astype(np.uint8)
        Image.fromarray(mask).save(os.path.join(scene, "mask0nocc.png"))
    with open(os.path.join(root, "MiddEval3", "official_train.txt"),
              "w") as f:
        f.write("\n".join(names) + "\n")


def make_all_benchmarks(datasets_root: str, seed: int = 7) -> str:
    """Build all four mini-benchmarks under ``datasets_root`` (the directory
    the reference's relative default roots resolve against when it is the
    CWD).  Returns ``datasets_root``."""
    rng = np.random.default_rng(seed)
    make_eth3d(os.path.join(datasets_root, "datasets", "ETH3D"), rng)
    make_kitti(os.path.join(datasets_root, "datasets", "KITTI"), rng)
    make_things(os.path.join(datasets_root, "datasets"), rng)
    make_middlebury(os.path.join(datasets_root, "datasets", "Middlebury"),
                    rng)
    return datasets_root
