"""Force hermetic CPU-only jax in THIS process.

Shared by tests/conftest.py and subprocess workers (distributed_worker.py):
this environment's sitecustomize registers a remote-TPU PJRT plugin ("axon")
at interpreter startup, the machine holds exactly ONE claim on the remote
chip, and a test process that touches it would serialize against (and wedge
behind) any other user of the chip.  One copy of the workaround so the two
call sites cannot drift.
"""

import os
import re


def force_cpu(n_devices: int = 8):
    """CPU backend with ``n_devices`` virtual devices; returns jax.

    Replaces (not merely appends) any inherited device-count flag — a
    subprocess worker spawned from the 8-device test process must get ITS
    requested count."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()

    try:  # deregister the remote-TPU plugin if sitecustomize installed it
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop("axon", None)
    except Exception:  # pragma: no cover - plugin absent elsewhere
        pass

    import jax

    # jax.config latched JAX_PLATFORMS at import time (sitecustomize imports
    # jax before we run) — update it explicitly.
    jax.config.update("jax_platforms", "cpu")
    return jax
