"""XL serving tier (round 17): mesh-sharded bucket executables, the
halo-overlap tiling fallback, and the device-group plumbing.

The headline pins are the acceptance criteria: a rows-mesh xl bucket
executable produces a gathered disparity matching the single-device
program (5e-4 at one GRU iteration — reassociation noise amplifies ~6x
per iteration through the correlation lookup on random weights, so
deeper pins would measure the weights' conditioning, not the sharding;
rows=1 is bitwise the solo program by construction), and tiling's
stitching math is exact on consistent fields (zero seam) while the seam
metric is live on inconsistent ones.  The full-model rows>=4 parity and
prewarm/readiness pins ride the slow tier (full mesh traces are ~tens
of seconds each on the CPU backend); scripts/xl_smoke.py runs the same
acceptance path in CI over real HTTP.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.eval.runner import InferenceRunner
from raft_stereo_tpu.models.raft_stereo import RAFTStereo
from raft_stereo_tpu.parallel.distributed import device_groups
from raft_stereo_tpu.parallel.mesh import mesh_spec_label, parse_mesh_spec
from raft_stereo_tpu.serving import (ServeConfig, ServingEngine, plan_tiles,
                                     seam_epe, stitch)
from raft_stereo_tpu.serving.persist import executable_cache_key


def _small_cfg(**kw):
    """The rows_gru test architecture (tests/test_rows_gru.py): 3 GRU
    levels, small dims, pure-XLA 'reg' corr."""
    base = dict(n_gru_layers=3, hidden_dims=(48, 48, 48), fnet_dim=96,
                corr_levels=2, corr_radius=3, corr_backend="reg")
    base.update(kw)
    return RaftStereoConfig(**base)


@pytest.fixture(scope="module")
def small_model():
    """ONE init shared by every engine test in this module — the
    parameter tree is architecture-determined, so configs that differ
    only in execution knobs (halo, mesh, thresholds) all consume it."""
    cfg = _small_cfg()
    model = RAFTStereo(cfg)
    img = jnp.zeros((1, 64, 64, 3), jnp.float32)
    variables = jax.jit(lambda r: model.init(r, img, img, iters=1,
                                             test_mode=True)
                        )(jax.random.PRNGKey(0))
    return cfg, variables


def _pair(rng, h, w):
    left = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    return left, np.roll(left, -4, axis=1)


# ------------------------------------------------------------ mesh specs
def test_parse_mesh_spec():
    assert parse_mesh_spec("rows=4") == {"rows": 4, "corr": 1}
    assert parse_mesh_spec("rows=2,corr=2") == {"rows": 2, "corr": 2}
    assert parse_mesh_spec(" corr=2 ") == {"rows": 1, "corr": 2}
    for bad in ("", "rows", "rows=0", "rows=x", "data=2", "rows=2,rows=2"):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad)


def test_mesh_spec_label():
    assert mesh_spec_label({"rows": 4, "corr": 1}) == "rows4"
    assert mesh_spec_label({"rows": 2, "corr": 2}) == "rows2corr2"
    assert mesh_spec_label({"rows": 1, "corr": 1}) == "solo"


# --------------------------------------------------------- device groups
def test_device_groups_partitions_disjoint():
    devs = jax.devices()
    groups = device_groups(2, devices=devs)
    assert len(groups) == len(devs) // 2
    flat = [d for g in groups for d in g]
    assert len(set(id(d) for d in flat)) == len(flat)   # disjoint
    # Stable id order: group 0 holds the lowest ids.
    assert [d.id for d in groups[0]] == sorted(d.id for d in devs)[:2]


def test_device_groups_skip_and_shortfall():
    devs = jax.devices()
    # skip=1 leaves device 0 (a solo worker) unassigned.
    groups = device_groups(4, n_groups=1, devices=devs, skip=1)
    assert len(groups) == 1
    assert devs[0].id not in [d.id for d in groups[0]]
    # Asking for more than fits is a typed EMPTY result, not an error.
    assert device_groups(len(devs) + 1, devices=devs) == []
    assert device_groups(4, n_groups=3, devices=devs) == []
    with pytest.raises(ValueError):
        device_groups(0)


# ----------------------------------------------------------------- tiles
def test_plan_tiles_geometry():
    specs = plan_tiles(512, tile_rows=128, halo=32)
    assert len(specs) == 4
    # Equal extents (one bucket => tiles batch together) and an exact
    # partition of the owned rows.
    assert len({s.height for s in specs}) == 1
    assert specs[0].height == 128 + 2 * 32
    assert specs[0].y0 == 0 and specs[-1].y1 == 512
    for a, b in zip(specs, specs[1:]):
        assert a.y1 == b.y0
    # Window extents stay inside the image (edge tiles shift inward).
    assert all(0 <= s.src0 and s.src1 <= 512 for s in specs)


def test_plan_tiles_single_when_short():
    specs = plan_tiles(100, tile_rows=128, halo=32)
    assert len(specs) == 1 and specs[0].src0 == 0 and specs[0].src1 == 100


def test_tiles_stitch_consistent_field_zero_seam(rng):
    """Tiles that are restrictions of ONE global field stitch back to it
    exactly, with zero seam error — the uniform-disparity property."""
    field = rng.uniform(-64, 0, (512, 96)).astype(np.float32)
    specs = plan_tiles(512, tile_rows=128, halo=32)
    flows = [field[s.src0:s.src1] for s in specs]
    out = stitch(flows, specs)
    np.testing.assert_array_equal(out, field)
    assert seam_epe(flows, specs) == 0.0


def test_tiles_seam_metric_fires_on_disagreement(rng):
    """Per-tile perturbations (what real tiling produces on textured
    content: each tile saw different vertical context) register in the
    seam metric."""
    field = rng.uniform(-64, 0, (512, 96)).astype(np.float32)
    specs = plan_tiles(512, tile_rows=128, halo=32)
    flows = [field[s.src0:s.src1] + 0.1 * i for i, s in enumerate(specs)]
    assert seam_epe(flows, specs) > 0.01
    # Single tile: nothing overlaps, the metric is typed absent.
    one = plan_tiles(100, tile_rows=128, halo=32)
    assert seam_epe([field[:100]], one) is None


# --------------------------------------------------------- persist keys
def test_xl_persist_keys_distinct():
    base = dict(config="{}", bucket=(512, 640), batch=1, tier=None,
                iters=32, fetch_dtype=None, donate=True, family=None,
                flow_init=False, quant="off", device="0")
    solo = executable_cache_key(**base)
    xl = executable_cache_key(**{**base, "family": "xl",
                                 "mesh": "rows4", "device": "0+1+2+3"})
    xl2 = executable_cache_key(**{**base, "family": "xl",
                                  "mesh": "rows2corr2",
                                  "device": "0+1+2+3"})
    assert len({solo, xl, xl2}) == 3


# ------------------------------------------------------- engine routing
def test_engine_without_xl_rejects_xl_tier(small_model, rng):
    cfg, v = small_model
    with ServingEngine(cfg, v, ServeConfig(iters=1)) as eng:
        assert not eng.xl_enabled
        assert eng.xl_status() is None
        left, right = _pair(rng, 64, 64)
        with pytest.raises(ValueError, match="no xl tier"):
            eng.submit(left, right, tier="xl")


def test_engine_xl_skips_typed_when_devices_short(small_model):
    """A replica whose devices cannot supply the mesh serves WITHOUT
    the tier (typed skip), instead of crashing at boot — the
    compile-farm / heterogeneous-fleet contract."""
    cfg, v = small_model
    with ServingEngine(cfg, v, ServeConfig(
            iters=1, xl_mesh=f"rows={2 * len(jax.devices())}")) as eng:
        assert not eng.xl_enabled
        # Big buckets quietly fall back to the solo/tiling routing.
        assert not eng._xl_routes((512, 64))


def test_engine_xl_incompatible_bucket_is_typed(small_model, rng):
    """A bucket that violates the mesh geometry (too few rows per
    shard) never auto-routes to xl, and forcing ?tier=xl on it is a
    typed client error."""
    cfg, v = small_model
    with ServingEngine(cfg, v, ServeConfig(
            iters=1, xl_mesh="rows=4", xl_threshold_pixels=100)) as eng:
        assert eng.xl_enabled
        ok, reason = eng._xl_compatible((64, 96))  # h_f=16: slab < 2*halo
        assert not ok and reason
        assert not eng._xl_routes((64, 96))
        left, right = _pair(rng, 64, 96)
        with pytest.raises(ValueError, match="does not fit mesh"):
            eng.submit(left, right, tier="xl")


def test_engine_xl_rows1_bitwise_and_tiling(small_model, rng):
    """One engine serving BOTH round-17 paths:

    * the degenerate rows=1 mesh — the xl family compiles the IDENTICAL
      solo program (make_forward_mesh falls back to make_forward), so
      the gathered output is bitwise the solo runner's;
    * a bucket past the mesh cap (xl_max_pixels) falls through to
      halo-overlap tiling: N equal tiles through ordinary bucket
      dispatches, one stitched full-res answer, seam metric observed —
      no new scheduler."""
    cfg, v = small_model
    left, right = _pair(rng, 64, 96)
    solo_flow, _ = InferenceRunner(cfg, v, iters=2)(left, right)
    with ServingEngine(cfg, v, ServeConfig(
            iters=2, xl_mesh="rows=1", xl_threshold_pixels=1000,
            xl_max_pixels=7000,
            tile_threshold_pixels=8000, tile_rows=64,
            tile_halo=16)) as eng:
        assert eng.xl_enabled
        # 64x96 = 6144 px: inside the xl band -> one mesh dispatch.
        res = eng.infer(left, right, timeout=300)
        assert res.tier == "xl" and res.mesh == "solo"
        np.testing.assert_array_equal(res.flow, solo_flow)
        assert eng.metrics.xl_dispatches.value == 1
        # 192x64 = 12288 px: past the mesh cap AND the tile threshold
        # -> 3 halo-overlap tiles (extent 96 rows each), stitched.
        tleft, tright = _pair(rng, 192, 64)
        tres = eng.infer(tleft, tright, timeout=600)
        assert tres.tiles == 3 and tres.tier is None
        assert tres.flow.shape == (192, 64)
        assert np.isfinite(tres.flow).all()
        assert tres.seam_epe is not None and tres.seam_epe >= 0.0
        assert eng.metrics.tiled_requests.value == 1
        assert eng.metrics.tile_seam_epe.count == 1
        # The three tiles ran as ordinary completed bucket requests.
        assert eng.metrics.completed.value == 1 + 3


@pytest.mark.slow
def test_engine_xl_rows4_parity_5e4(small_model, rng):
    """The acceptance pin: an xl bucket executable sharded over a
    rows=4 mesh on the 8-virtual-device CPU backend produces a gathered
    disparity within 5e-4 of the single-device program, with a distinct
    ',mesh=rows4' cost record whose per-device HBM sits strictly below
    the solo record's."""
    cfg, v = small_model
    H, W = 512, 64
    left, right = _pair(rng, H, W)
    solo_flow, _ = InferenceRunner(cfg, v, iters=1)(left, right)
    with ServingEngine(cfg, v, ServeConfig(
            iters=1, xl_mesh="rows=4", xl_threshold_pixels=10_000,
            cost_telemetry=True)) as eng:
        assert eng.xl_enabled
        res = eng.infer(left, right, timeout=600)
        assert res.tier == "xl" and res.mesh == "rows4"
        assert float(np.abs(res.flow - solo_flow).max()) < 5e-4
        rec = eng.compiled_cost((H, W), 1, family="xl")
        assert rec is not None and ",mesh=rows4" in rec.key
        xl_hbm = rec.hbm_bytes
    with ServingEngine(cfg, v, ServeConfig(
            iters=1, cost_telemetry=True)) as solo_eng:
        solo_eng.infer(left, right, timeout=600)
        solo_rec = solo_eng.compiled_cost((H, W), 1)
    if xl_hbm and solo_rec is not None and solo_rec.hbm_bytes:
        assert xl_hbm < solo_rec.hbm_bytes


@pytest.mark.slow
def test_xl_warm_target_and_readiness(small_model, rng):
    """An xl-routed warmup shape puts the XL ladder (not the solo
    ladder) on the readiness surface, and prewarm opens the gate."""
    cfg, v = small_model
    import dataclasses
    cfg = dataclasses.replace(cfg, rows_gru_halo=8)
    H, W = 128, 64     # h_f=32, slab 16 = 2*halo -> mesh-compatible
    serve_cfg = ServeConfig(
        iters=1, xl_mesh="rows=2", xl_threshold_pixels=4000,
        warmup_shapes=((H, W),), prewarm_on_init=False)
    with ServingEngine(cfg, v, serve_cfg) as eng:
        assert eng.xl_enabled
        assert not eng.ready
        with eng._warm_lock:
            target = set(eng._warm_target)
        assert all(entry[4] == "xl" for entry in target)
        eng.prewarm((H, W))
        assert eng.ready
        # Traffic at the warmed bucket dispatches xl without compiling.
        res = eng.infer(*_pair(rng, H, W), timeout=300)
        assert res.tier == "xl"
