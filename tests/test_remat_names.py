"""Every name in config.remat_save must exist as a checkpoint_name tag in
the traced train-mode graph.

The remat policy is ``save_only_these_names(*cfg.remat_save)``: a tag that
silently disappears (e.g. renamed, or dropped when a computation moves into
a fused kernel) turns the save-policy into save-nothing — training still
produces correct numbers but the backward recomputes everything, blowing up
step time/memory with no error anywhere.  This test walks the traced
jaxpr for ``name`` primitives and pins the full tag set on both the Flax
and fused-GRU paths.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.models.raft_stereo import RAFTStereo

ALL_SAVE_NAMES = ("corr_lookup", "gru_gates", "motion_features")


def _collect_checkpoint_names(jaxpr) -> set:
    """All checkpoint_name tags (``name`` primitive params) in a jaxpr,
    recursing into every sub-jaxpr (scan/remat/custom-vjp bodies)."""
    names = set()

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "name":
                names.add(eqn.params["name"])
            for sub in jax.core.jaxprs_in_params(eqn.params):
                walk(sub)

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)
    return names


def _traced_names(cfg) -> set:
    model = RAFTStereo(cfg)
    img = jnp.zeros((1, 32, 48, 3), jnp.float32)
    v = model.init(jax.random.PRNGKey(0), img, img, iters=1, test_mode=True)
    jaxpr = jax.make_jaxpr(
        lambda v_, a, b: model.apply(v_, a, b, iters=2))(v, img, img)
    return _collect_checkpoint_names(jaxpr)


def test_remat_save_names_present_flax_path():
    cfg = RaftStereoConfig(hidden_dims=(16, 16), n_gru_layers=2,
                           fnet_dim=32, corr_levels=2, corr_radius=3,
                           fused_gru="off", remat_save=ALL_SAVE_NAMES)
    names = _traced_names(cfg)
    missing = set(cfg.remat_save) - names
    assert not missing, (
        f"remat_save names {sorted(missing)} are not tagged anywhere in the "
        f"train-mode graph (found {sorted(names)}) — the save policy for "
        "them is silently a no-op")


def test_remat_save_names_present_fused_path():
    """The fused ConvGRU kernel must keep tagging its gate outputs: the
    "gru_gates" site moved from the Flax conv outputs onto the kernel's
    (zr, qpre) outputs and must not be lost."""
    from raft_stereo_tpu.kernels import corr_lookup

    corr_lookup._interpret_override = True
    try:
        cfg = RaftStereoConfig(hidden_dims=(16, 16), n_gru_layers=2,
                               fnet_dim=32, corr_levels=2, corr_radius=3,
                               fused_gru="on", remat_save=ALL_SAVE_NAMES)
        names = _traced_names(cfg)
    finally:
        corr_lookup._interpret_override = None
    missing = set(cfg.remat_save) - names
    assert not missing, (
        f"remat_save names {sorted(missing)} vanished from the fused-GRU "
        f"train-mode graph (found {sorted(names)})")


def test_unknown_remat_name_still_rejected():
    """Config-level guard stays intact (complements the graph-level pin)."""
    with pytest.raises(ValueError, match="remat_save"):
        RaftStereoConfig(remat_save=("gru_gates", "renamed_tag"))
