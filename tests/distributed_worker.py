"""Worker for the real 2-process distributed test (test_distributed.py).

Each process: ``jax.distributed.initialize`` over a localhost coordinator,
2 local virtual CPU devices (4 global), a (4, 1) mesh spanning both
processes, and two SPMD train steps where each process contributes only its
LOCAL slice of the global batch (``shard_batch`` →
``jax.make_array_from_process_local_data`` — the branch single-process runs
can never reach).  Writes the final params and losses for the parent test
to compare across processes and against a single-process run.

Usage: python distributed_worker.py <pid> <nproc> <coord_addr> <out.npz>
"""

import os
import sys


def main():
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    coord, out_path = sys.argv[3], sys.argv[4]

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _hermetic import force_cpu

    jax = force_cpu(2)

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc
    assert jax.device_count() == 2 * nproc

    import jax.numpy as jnp
    import numpy as np

    from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
    from raft_stereo_tpu.parallel import distributed
    from raft_stereo_tpu.parallel.mesh import make_mesh, replicate, shard_batch
    from raft_stereo_tpu.training.state import create_train_state
    from raft_stereo_tpu.training.step import make_train_step

    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), corr_levels=2,
                            fnet_dim=32)
    tcfg = TrainConfig(batch_size=8, train_iters=2, num_steps=10,
                      image_size=(32, 48))
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               image_shape=(1, 32, 48, 3))
    mesh = make_mesh(n_data=4)
    state = replicate(state, mesh)
    step_fn = make_train_step(tcfg, mesh=mesh, donate=False)

    # the stop-flag collective the train loop runs each step
    assert distributed.any_process(False) is False
    assert distributed.any_process(pid == 0) is True

    local = 8 // nproc
    losses = []
    for step in range(2):
        rng = np.random.default_rng(100 + step)  # same GLOBAL batch everywhere
        g = {
            "image1": rng.uniform(0, 255, (8, 32, 48, 3)).astype(np.float32),
            "image2": rng.uniform(0, 255, (8, 32, 48, 3)).astype(np.float32),
            "flow": rng.normal(0, 5, (8, 32, 48)).astype(np.float32),
            "valid": np.ones((8, 32, 48), np.float32),
        }
        local_batch = {k: v[pid * local:(pid + 1) * local] for k, v in g.items()}
        state, metrics = step_fn(state, shard_batch(local_batch, mesh))
        losses.append(float(metrics["loss"]))

    # fully-replicated state: every process can read it
    flat = np.concatenate([np.ravel(np.asarray(jax.device_get(x)))
                           for x in jax.tree_util.tree_leaves(state.params)])
    np.savez(out_path, params=flat, losses=np.asarray(losses))
    print(f"worker {pid}: done, losses {losses}")


if __name__ == "__main__":
    main()
