"""Worker for the real 2-process distributed tests (test_distributed.py).

Each process: ``jax.distributed.initialize`` over a localhost coordinator,
2 local virtual CPU devices (4 global), a mesh spanning both processes, and
two SPMD train steps.  Two modes:

* ``data`` — a (4,) data mesh; each process contributes only its LOCAL
  slice of the global batch (``shard_batch`` →
  ``jax.make_array_from_process_local_data`` — the branch single-process
  runs can never reach).
* ``rows`` — a (data=2, corr=1, rows=2) mesh with the ROWS axis laid
  ACROSS the two processes (device order [p0d0, p1d0, p0d1, p1d1]), so the
  full-loop context-parallel executor's per-iteration halo ``ppermute``
  rides the cross-process link — the multi-host analog of sequence
  parallelism over DCN.  Each process passes the full global batch (its
  devices hold a piece of every sample).

Writes the final params and losses for the parent test to compare across
processes and against a single-process run.

Usage: python distributed_worker.py <pid> <nproc> <coord> <out.npz> [mode]
"""

import os
import sys


def main():
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    coord, out_path = sys.argv[3], sys.argv[4]
    mode = sys.argv[5] if len(sys.argv) > 5 else "data"

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _hermetic import force_cpu

    jax = force_cpu(2)

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc
    assert jax.device_count() == 2 * nproc

    import contextlib

    import jax.numpy as jnp
    import numpy as np

    from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
    from raft_stereo_tpu.parallel import distributed
    from raft_stereo_tpu.parallel.mesh import (ROWS_AXIS, make_mesh,
                                               replicate, shard_batch)
    from raft_stereo_tpu.parallel.rows_sharded import rows_sharding
    from raft_stereo_tpu.training.state import create_train_state
    from raft_stereo_tpu.training.step import make_train_step

    if mode == "rows":
        mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,),
                                corr_levels=2, fnet_dim=32,
                                rows_shards=2, rows_gru=True,
                                rows_gru_halo=12)
        h, w, batch = 192, 64, 2
        tcfg = TrainConfig(batch_size=batch, train_iters=2, num_steps=10,
                           image_size=(h, w), data_parallel=2)
        # rows ACROSS processes: grid[data, corr, rows] with rows pairs
        # (p0d0, p1d0) and (p0d1, p1d1).
        devs = jax.devices()
        mesh = make_mesh(n_data=2, n_corr=1, n_rows=2,
                         devices=[devs[0], devs[2], devs[1], devs[3]])
        mesh_ctx = lambda: rows_sharding(mesh, axis=ROWS_AXIS)  # noqa: E731
    else:
        mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,),
                                corr_levels=2, fnet_dim=32)
        h, w, batch = 32, 48, 8
        tcfg = TrainConfig(batch_size=batch, train_iters=2, num_steps=10,
                           image_size=(h, w))
        mesh = make_mesh(n_data=4)
        mesh_ctx = contextlib.nullcontext

    with mesh_ctx():
        state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                                   image_shape=(1, h, w, 3))
    state = replicate(state, mesh)
    step_fn = make_train_step(tcfg, mesh=mesh, donate=False)

    # the stop-flag collective the train loop runs each step
    assert distributed.any_process(False) is False
    assert distributed.any_process(pid == 0) is True

    local = batch // nproc
    losses = []
    for step in range(2):
        rng = np.random.default_rng(100 + step)  # same GLOBAL batch everywhere
        g = {
            "image1": rng.uniform(0, 255, (batch, h, w, 3)).astype(np.float32),
            "image2": rng.uniform(0, 255, (batch, h, w, 3)).astype(np.float32),
            "flow": rng.normal(0, 5, (batch, h, w)).astype(np.float32),
            "valid": np.ones((batch, h, w), np.float32),
        }
        if mode == "rows":
            # rows spans processes, so every process's devices hold a piece
            # of every sample — the process-local data IS the global batch.
            local_batch = g
        else:
            local_batch = {k: v[pid * local:(pid + 1) * local]
                           for k, v in g.items()}
        with mesh_ctx():
            state, metrics = step_fn(state, shard_batch(local_batch, mesh))
        losses.append(float(metrics["loss"]))

    # fully-replicated state: every process can read it
    flat = np.concatenate([np.ravel(np.asarray(jax.device_get(x)))
                           for x in jax.tree_util.tree_leaves(state.params)])
    np.savez(out_path, params=flat, losses=np.asarray(losses))
    print(f"worker {pid}: done, losses {losses}")


if __name__ == "__main__":
    main()
