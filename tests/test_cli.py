"""End-to-end CLI + training-loop tests on synthetic data (CPU)."""

import glob
import os

import numpy as np
import pytest
from PIL import Image

from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.data.datasets import KITTI
from raft_stereo_tpu.data.loader import StereoLoader

pytestmark = pytest.mark.slow  # full-model / subprocess-scale tests

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64)  # fast CPU compiles


def _make_kitti_tree(root, n=3, size=(64, 96)):
    h, w = size
    rng = np.random.default_rng(0)
    for sub in ("image_2", "image_3", "disp_occ_0"):
        (root / "training" / sub).mkdir(parents=True)
    for i in range(n):
        left = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
        Image.fromarray(left).save(
            root / "training" / "image_2" / f"{i:06d}_10.png")
        Image.fromarray(np.roll(left, -3, axis=1)).save(
            root / "training" / "image_3" / f"{i:06d}_10.png")
        frame_utils.write_disp_kitti(
            str(root / "training" / "disp_occ_0" / f"{i:06d}_10.png"),
            np.full((h, w), 3.0, np.float32))
    return root


@pytest.fixture(scope="module")
def tiny_checkpoint(tmp_path_factory):
    """A saved orbax checkpoint of a tiny random-init model."""
    import jax

    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.training.checkpoint import save_weights

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    import jax.numpy as jnp
    dummy = jnp.zeros((1, 64, 96, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    path = str(tmp_path_factory.mktemp("ckpt") / "tiny")
    save_weights(path, cfg, variables["params"],
                 variables.get("batch_stats"))
    return path


def test_demo_cli(tmp_path, tiny_checkpoint):
    from raft_stereo_tpu.cli.demo import main

    root = _make_kitti_tree(tmp_path / "KITTI")
    out = tmp_path / "out"
    main(["--restore_ckpt", tiny_checkpoint,
          "-l", str(root / "training" / "image_2" / "*_10.png"),
          "-r", str(root / "training" / "image_3" / "*_10.png"),
          "--output_directory", str(out),
          "--save_numpy", "--valid_iters", "2"])
    pngs = sorted(glob.glob(str(out / "*-disparity.png")))
    npys = sorted(glob.glob(str(out / "*.npy")))
    assert len(pngs) == 3 and len(npys) == 3
    disp = np.load(npys[0])
    assert disp.shape == (64, 96) and np.isfinite(disp).all()


def test_demo_cli_sequence_mode(tmp_path, tiny_checkpoint, caplog):
    """--sequence runs the frames in order with warm-start chaining and
    logs per-frame iters_used + cumulative FPS (round-14 satellite)."""
    import logging

    from raft_stereo_tpu.cli.demo import main

    root = _make_kitti_tree(tmp_path / "KITTI")
    out = tmp_path / "seq_out"
    with caplog.at_level(logging.INFO):
        main(["--restore_ckpt", tiny_checkpoint,
              "-l", str(root / "training" / "image_2" / "*_10.png"),
              "-r", str(root / "training" / "image_3" / "*_10.png"),
              "--output_directory", str(out), "--sequence",
              "--valid_iters", "2", "--exit_threshold_px", "1e9"])
    pngs = sorted(glob.glob(str(out / "*-disparity.png")))
    assert len(pngs) == 3
    text = caplog.text
    assert "frame 0 cold" in text
    assert "frame 1 warm" in text and "frame 2 warm" in text
    assert "cumulative" in text and "sequence done" in text


def test_evaluate_cli(tmp_path, tiny_checkpoint, capsys):
    from raft_stereo_tpu.cli.evaluate import main

    _make_kitti_tree(tmp_path / "KITTI")
    results = main(["--restore_ckpt", tiny_checkpoint,
                    "--dataset", "kitti",
                    "--data_root", str(tmp_path),
                    "--valid_iters", "2", "--max_images", "2"])
    assert "kitti-epe" in results and "kitti-d1" in results
    assert np.isfinite(results["kitti-epe"])


def test_evaluate_cli_sequence_mode(tmp_path, tiny_checkpoint):
    """--sequence reports warm-start EPE drift vs cold per-frame
    inference and records it to --stream_out (round-14 satellite)."""
    import json

    from raft_stereo_tpu.cli.evaluate import main

    _make_kitti_tree(tmp_path / "KITTI")
    out = tmp_path / "STREAM_test.json"
    results = main(["--restore_ckpt", tiny_checkpoint,
                    "--dataset", "kitti", "--data_root", str(tmp_path),
                    "--valid_iters", "2", "--max_images", "2",
                    "--sequence", "--stream_out", str(out)])
    for key in ("kitti-epe-cold", "kitti-epe-warm",
                "kitti-warm-drift-epe"):
        assert key in results and np.isfinite(results[key])
    assert results["kitti-warm-drift-epe"] == pytest.approx(
        results["kitti-epe-warm"] - results["kitti-epe-cold"])
    rec = json.loads(out.read_text())
    assert rec["metric"] == "warm_start_sequence_drift"
    assert rec["dataset"] == "kitti" and "results" in rec


def test_train_loop_and_exact_resume(tmp_path):
    from raft_stereo_tpu.training.train_loop import train

    root = _make_kitti_tree(tmp_path / "KITTI", n=4)
    model_cfg = RaftStereoConfig(**TINY)
    train_cfg = TrainConfig(batch_size=2, train_iters=2, num_steps=3,
                            image_size=(48, 64), data_parallel=2,
                            validation_frequency=2, seed=7)
    aug = {"crop_size": (48, 64), "min_scale": -0.2, "max_scale": 0.4,
           "do_flip": None, "yjitter": False}
    ds = KITTI(aug_params=aug, root=str(root))
    loader = StereoLoader(ds, batch_size=2, num_workers=0, seed=7)

    ckpt_dir = str(tmp_path / "ckpts")
    state = train(model_cfg, train_cfg, name="t", data_root="unused",
                  checkpoint_dir=ckpt_dir, log_dir=str(tmp_path / "runs"),
                  loader=loader)
    assert int(state.step) == 3
    assert os.path.isdir(os.path.join(ckpt_dir, "t"))

    # exact resume continues from the saved step with optimizer state intact
    train_cfg2 = TrainConfig(**{**train_cfg.to_dict(), "num_steps": 5})
    loader2 = StereoLoader(ds, batch_size=2, num_workers=0, seed=7)
    state2 = train(model_cfg, train_cfg2, name="t2", data_root="unused",
                   checkpoint_dir=ckpt_dir, log_dir=str(tmp_path / "runs2"),
                   restore=os.path.join(ckpt_dir, "t"), loader=loader2)
    assert int(state2.step) == 5


def test_train_cli_with_periodic_validation(tmp_path, capsys):
    """The reference's every-N-steps validation regression check
    (train_stereo.py:183-193), wired through the CLI: a 2-step run on a
    synthetic KITTI tree validates at step 2 and logs the metrics dict."""
    from raft_stereo_tpu.cli import train as train_cli

    _make_kitti_tree(tmp_path / "KITTI", n=4, size=(64, 96))
    state = train_cli.main([
        "--name", "t", "--data_root", str(tmp_path),
        "--checkpoint_dir", str(tmp_path / "ck"),
        "--log_dir", str(tmp_path / "runs"),
        "--train_datasets", "kitti", "--batch_size", "2", "--num_steps", "2",
        "--train_iters", "2", "--valid_iters", "2",
        "--image_size", "48", "64", "--hidden_dims", "32", "32", "32",
        "--validate_datasets", "kitti", "--validation_frequency", "2",
        "--validate_max_images", "2", "--data_parallel", "2",
    ])
    assert int(state.step) == 2
    out = capsys.readouterr().out
    assert "Validation kitti" in out


def test_runner_cache_bounded_and_bucketed(tiny_checkpoint):
    """Per-shape compile cache evicts LRU-style, and shape_bucket collapses
    nearby shapes into one compiled program."""
    import numpy as np

    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.training.checkpoint import load_weights

    cfg, variables = load_weights(tiny_checkpoint)
    runner = InferenceRunner(cfg, variables, iters=1, max_cached_shapes=2)
    rng = np.random.default_rng(0)
    for h, w in ((32, 64), (64, 64), (64, 96), (32, 64)):
        img = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
        flow, _ = runner(img, img)
        assert flow.shape == (h, w)
    assert len(runner._compiled) == 2  # bounded; oldest evicted

    bucketed = InferenceRunner(cfg, variables, iters=1, shape_bucket=64)
    for h, w in ((60, 90), (62, 94), (33, 65)):
        img = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
        flow, _ = bucketed(img, img)
        assert flow.shape == (h, w)  # exact unpad regardless of bucket
    assert len(bucketed._compiled) == 1  # all bucket to (64, 128)


def test_runner_batched_matches_per_image(tiny_checkpoint):
    """run_batch (one upload / one forward / one fetch for N pairs) returns
    the same flows as N per-image calls — the throughput product mode."""
    import numpy as np

    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.training.checkpoint import load_weights

    cfg, variables = load_weights(tiny_checkpoint)
    runner = InferenceRunner(cfg, variables, iters=2)
    rng = np.random.default_rng(3)
    lefts = [rng.uniform(0, 255, (60, 90, 3)).astype(np.uint8)
             for _ in range(3)]
    rights = [np.roll(l, -3, axis=1) for l in lefts]

    flows, secs = runner.run_batch(lefts, rights)
    assert flows.shape == (3, 60, 90) and secs > 0
    for i in range(3):
        per_img, _ = runner(lefts[i], rights[i])
        # batch-3 and batch-1 are different executables; XLA layout/fusion
        # reassociation drifts a few 1e-5 on O(10) flows
        np.testing.assert_allclose(flows[i], per_img, atol=5e-4)

    with pytest.raises(AssertionError, match="same-shape"):
        runner.run_batch([lefts[0], lefts[1][:32]],
                         [rights[0], rights[1][:32]])


@pytest.mark.quick  # overrides the module slow mark: runner-construction only
def test_runner_deep_iters_bf16_corr_guard():
    """iters >= DEEP_ITERS_FP32_CORR with bf16 corr flips corr_fp32 in the
    runner's effective config (measured 32-iter drift, BF16_DRIFT_r03.json);
    the as-given config is preserved for identity comparisons, and
    corr_fp32_auto=False opts out (tools/bf16_drift.py measures raw bf16)."""
    import dataclasses

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.eval.runner import DEEP_ITERS_FP32_CORR, InferenceRunner

    cfg = RaftStereoConfig(mixed_precision=True)
    assert not cfg.corr_fp32
    deep = InferenceRunner(cfg, {}, iters=DEEP_ITERS_FP32_CORR)
    assert deep.effective_config.corr_fp32
    assert deep.config == cfg  # make_validation_fn compares this
    assert deep.effective_config == dataclasses.replace(cfg, corr_fp32=True)

    shallow = InferenceRunner(cfg, {}, iters=7)
    assert not shallow.effective_config.corr_fp32

    opted_out = InferenceRunner(cfg, {}, iters=32, corr_fp32_auto=False)
    assert not opted_out.effective_config.corr_fp32

    fp32_cfg = RaftStereoConfig()  # no mixed precision -> nothing to guard
    assert not InferenceRunner(fp32_cfg, {},
                               iters=32).effective_config.corr_fp32


def test_train_cli_rows_gru(tmp_path):
    """Full-loop context parallelism from the user-facing surface: the one
    -flag UX the reference gives DataParallel (train_stereo.py:134), here
    ``--rows_shards 2 --rows_gru``.  Launches a real training step through
    cli.train on a 2-device rows mesh (1 data x 1 corr x 2 rows)."""
    from raft_stereo_tpu.cli import train as train_cli

    # fine level = 192/4 = 48 rows -> slab 24 = 2*halo at halo=12
    _make_kitti_tree(tmp_path / "KITTI", n=4, size=(192, 96))
    state = train_cli.main([
        "--name", "rg", "--data_root", str(tmp_path),
        "--checkpoint_dir", str(tmp_path / "ck"),
        "--log_dir", str(tmp_path / "runs"),
        "--train_datasets", "kitti", "--batch_size", "1", "--num_steps", "1",
        "--train_iters", "2", "--valid_iters", "2",
        "--image_size", "192", "64", "--hidden_dims", "32", "32", "32",
        "--data_parallel", "1",
        "--rows_shards", "2", "--rows_gru", "--rows_gru_halo", "12",
    ])
    assert int(state.step) == 1
