"""End-to-end CLI + training-loop tests on synthetic data (CPU)."""

import glob
import os

import numpy as np
import pytest
from PIL import Image

from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.data.datasets import KITTI
from raft_stereo_tpu.data.loader import StereoLoader

pytestmark = pytest.mark.slow  # full-model / subprocess-scale tests

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64)  # fast CPU compiles


def _make_kitti_tree(root, n=3, size=(64, 96)):
    h, w = size
    rng = np.random.default_rng(0)
    for sub in ("image_2", "image_3", "disp_occ_0"):
        (root / "training" / sub).mkdir(parents=True)
    for i in range(n):
        left = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
        Image.fromarray(left).save(
            root / "training" / "image_2" / f"{i:06d}_10.png")
        Image.fromarray(np.roll(left, -3, axis=1)).save(
            root / "training" / "image_3" / f"{i:06d}_10.png")
        frame_utils.write_disp_kitti(
            str(root / "training" / "disp_occ_0" / f"{i:06d}_10.png"),
            np.full((h, w), 3.0, np.float32))
    return root


@pytest.fixture(scope="module")
def tiny_checkpoint(tmp_path_factory):
    """A saved orbax checkpoint of a tiny random-init model."""
    import jax

    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.training.checkpoint import save_weights

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    import jax.numpy as jnp
    dummy = jnp.zeros((1, 64, 96, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    path = str(tmp_path_factory.mktemp("ckpt") / "tiny")
    save_weights(path, cfg, variables["params"],
                 variables.get("batch_stats"))
    return path


def test_demo_cli(tmp_path, tiny_checkpoint):
    from raft_stereo_tpu.cli.demo import main

    root = _make_kitti_tree(tmp_path / "KITTI")
    out = tmp_path / "out"
    main(["--restore_ckpt", tiny_checkpoint,
          "-l", str(root / "training" / "image_2" / "*_10.png"),
          "-r", str(root / "training" / "image_3" / "*_10.png"),
          "--output_directory", str(out),
          "--save_numpy", "--valid_iters", "2"])
    pngs = sorted(glob.glob(str(out / "*-disparity.png")))
    npys = sorted(glob.glob(str(out / "*.npy")))
    assert len(pngs) == 3 and len(npys) == 3
    disp = np.load(npys[0])
    assert disp.shape == (64, 96) and np.isfinite(disp).all()


def test_evaluate_cli(tmp_path, tiny_checkpoint, capsys):
    from raft_stereo_tpu.cli.evaluate import main

    _make_kitti_tree(tmp_path / "KITTI")
    results = main(["--restore_ckpt", tiny_checkpoint,
                    "--dataset", "kitti",
                    "--data_root", str(tmp_path),
                    "--valid_iters", "2", "--max_images", "2"])
    assert "kitti-epe" in results and "kitti-d1" in results
    assert np.isfinite(results["kitti-epe"])


def test_train_loop_and_exact_resume(tmp_path):
    from raft_stereo_tpu.training.train_loop import train

    root = _make_kitti_tree(tmp_path / "KITTI", n=4)
    model_cfg = RaftStereoConfig(**TINY)
    train_cfg = TrainConfig(batch_size=2, train_iters=2, num_steps=3,
                            image_size=(48, 64), data_parallel=2,
                            validation_frequency=2, seed=7)
    aug = {"crop_size": (48, 64), "min_scale": -0.2, "max_scale": 0.4,
           "do_flip": None, "yjitter": False}
    ds = KITTI(aug_params=aug, root=str(root))
    loader = StereoLoader(ds, batch_size=2, num_workers=0, seed=7)

    ckpt_dir = str(tmp_path / "ckpts")
    state = train(model_cfg, train_cfg, name="t", data_root="unused",
                  checkpoint_dir=ckpt_dir, log_dir=str(tmp_path / "runs"),
                  loader=loader)
    assert int(state.step) == 3
    assert os.path.isdir(os.path.join(ckpt_dir, "t"))

    # exact resume continues from the saved step with optimizer state intact
    train_cfg2 = TrainConfig(**{**train_cfg.to_dict(), "num_steps": 5})
    loader2 = StereoLoader(ds, batch_size=2, num_workers=0, seed=7)
    state2 = train(model_cfg, train_cfg2, name="t2", data_root="unused",
                   checkpoint_dir=ckpt_dir, log_dir=str(tmp_path / "runs2"),
                   restore=os.path.join(ckpt_dir, "t"), loader=loader2)
    assert int(state2.step) == 5
