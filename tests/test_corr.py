"""Correlation backends: cross-checked against each other and against the
reference math re-derived in torch (the reference's implicit test strategy —
three live implementations of one contract, SURVEY.md §4)."""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.models.corr import (
    build_corr_pyramid, build_corr_volume, make_corr_fn, pool_last_axis)


def _torch_reg_lookup(fmap1, fmap2, coords, num_levels, radius):
    """Reference CorrBlock1D math (core/corr.py:110-156) in torch, NCHW."""
    f1 = torch.from_numpy(fmap1).permute(0, 3, 1, 2)  # (B,D,H,W1)
    f2 = torch.from_numpy(fmap2).permute(0, 3, 1, 2)
    B, D, H, W1 = f1.shape
    W2 = f2.shape[3]
    corr = torch.einsum("aijk,aijh->ajkh", f1, f2)
    corr = corr.reshape(B, H, W1, 1, W2) / torch.sqrt(torch.tensor(float(D)))
    corr = corr.reshape(B * H * W1, 1, 1, W2)

    pyramid = [corr]
    for _ in range(num_levels):
        corr = F.avg_pool2d(corr, [1, 2], stride=[1, 2])
        pyramid.append(corr)

    c = torch.from_numpy(coords)  # (B,H,W1)
    out_pyramid = []
    for i in range(num_levels):
        vol = pyramid[i]
        w = vol.shape[-1]
        dx = torch.linspace(-radius, radius, 2 * radius + 1).view(1, 1, -1, 1)
        x0 = dx + c.reshape(B * H * W1, 1, 1, 1) / 2 ** i
        y0 = torch.zeros_like(x0)
        xgrid = 2 * x0 / (w - 1) - 1
        grid = torch.cat([xgrid, y0], dim=-1)
        samp = F.grid_sample(vol, grid, align_corners=True)
        out_pyramid.append(samp.view(B, H, W1, -1))
    return torch.cat(out_pyramid, dim=-1).numpy()  # (B,H,W1,L*(2r+1))


@pytest.fixture
def fmaps(rng):
    B, H, W, D = 2, 6, 40, 32
    f1 = rng.standard_normal((B, H, W, D)).astype(np.float32)
    f2 = rng.standard_normal((B, H, W, D)).astype(np.float32)
    # coords roaming over and slightly beyond the valid range
    coords = rng.uniform(-3, W + 2, size=(B, H, W)).astype(np.float32)
    return f1, f2, coords


def test_volume_matches_reference_einsum(fmaps):
    f1, f2, _ = fmaps
    got = build_corr_volume(jnp.asarray(f1), jnp.asarray(f2))
    t1 = torch.from_numpy(f1).permute(0, 3, 1, 2)
    t2 = torch.from_numpy(f2).permute(0, 3, 1, 2)
    want = torch.einsum("aijk,aijh->ajkh", t1, t2) / np.sqrt(f1.shape[-1])
    np.testing.assert_allclose(np.asarray(got), want.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_pool_last_axis_floor_semantics(rng):
    x = rng.standard_normal((2, 3, 7)).astype(np.float32)  # odd width
    got = pool_last_axis(jnp.asarray(x))
    assert got.shape == (2, 3, 3)
    want = F.avg_pool2d(torch.from_numpy(x)[:, None], [1, 2],
                        stride=[1, 2]).numpy()[:, 0]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


@pytest.mark.slow
def test_reg_matches_torch_reference(fmaps):
    f1, f2, coords = fmaps
    cfg = RaftStereoConfig(corr_levels=4, corr_radius=4, corr_backend="reg")
    corr_fn = make_corr_fn(cfg, jnp.asarray(f1), jnp.asarray(f2))
    got = np.asarray(corr_fn(jnp.asarray(coords)))
    want = _torch_reg_lookup(f1, f2, coords, 4, 4)
    assert got.shape == want.shape == (2, 6, 40, 36)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_alt_matches_reg_at_integer_coords(fmaps, rng):
    """alt computes level-i correlation from POOLED FEATURES, reg from the
    POOLED VOLUME — identical at level 0 and linear-combination-equal
    elsewhere only for matching pooling, so compare level 0 exactly and all
    levels against the torch alt reference below."""
    f1, f2, _ = fmaps
    B, H, W, _ = f1.shape
    coords = rng.integers(0, W, size=(B, H, W)).astype(np.float32)
    cfg1 = RaftStereoConfig(corr_levels=1, corr_radius=4, corr_backend="reg")
    cfg2 = RaftStereoConfig(corr_levels=1, corr_radius=4, corr_backend="alt")
    reg = np.asarray(make_corr_fn(cfg1, jnp.asarray(f1), jnp.asarray(f2))(
        jnp.asarray(coords)))
    alt = np.asarray(make_corr_fn(cfg2, jnp.asarray(f1), jnp.asarray(f2))(
        jnp.asarray(coords)))
    np.testing.assert_allclose(reg, alt, rtol=1e-4, atol=1e-4)


def test_alt_matches_torch_alt(fmaps):
    """Against PytorchAlternateCorrBlock1D math (core/corr.py:64-107)."""
    f1, f2, coords = fmaps
    B, H, W, D = f1.shape
    cfg = RaftStereoConfig(corr_levels=4, corr_radius=4, corr_backend="alt")
    got = np.asarray(make_corr_fn(cfg, jnp.asarray(f1), jnp.asarray(f2))(
        jnp.asarray(coords)))

    t1 = torch.from_numpy(f1).permute(0, 3, 1, 2)
    t2 = torch.from_numpy(f2).permute(0, 3, 1, 2)
    c = torch.from_numpy(coords)                      # (B,H,W) x positions
    ys = torch.arange(H).float().view(1, H, 1).expand(B, H, W)
    r = 4
    out_pyramid = []
    f2_i = t2
    for i in range(4):
        Wi = f2_i.shape[3]
        dx = torch.linspace(-r, r, 2 * r + 1)
        x_taps = c[..., None] / 2 ** i + dx            # (B,H,W,K)
        xgrid = 2 * x_taps / (Wi - 1) - 1
        ygrid = (2 * ys / (H - 1) - 1)[..., None].expand_as(xgrid)
        corr_k = []
        for k in range(2 * r + 1):
            grid = torch.stack([xgrid[..., k], ygrid[..., k]], dim=-1)
            samp = F.grid_sample(f2_i, grid, align_corners=True)
            corr_k.append((samp * t1).sum(dim=1))
        out_pyramid.append(torch.stack(corr_k, dim=-1) / np.sqrt(D))
        f2_i = F.avg_pool2d(f2_i, [1, 2], stride=[1, 2])
    want = torch.cat(out_pyramid, dim=-1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_reg_fused_falls_back_and_matches_reg(fmaps):
    f1, f2, coords = fmaps
    reg = make_corr_fn(RaftStereoConfig(corr_backend="reg"),
                       jnp.asarray(f1), jnp.asarray(f2))
    fused = make_corr_fn(RaftStereoConfig(corr_backend="reg_fused"),
                         jnp.asarray(f1), jnp.asarray(f2))
    np.testing.assert_allclose(np.asarray(fused(jnp.asarray(coords))),
                               np.asarray(reg(jnp.asarray(coords))),
                               rtol=1e-4, atol=1e-4)


def test_pyramid_shapes():
    corr = jnp.zeros((1, 4, 10, 37))
    pyr = build_corr_pyramid(corr, 4)
    assert [p.shape[-1] for p in pyr] == [37, 18, 9, 4]


@pytest.mark.slow
def test_corr_fp32_knob_forces_fp32_under_bf16(fmaps):
    """corr_fp32=True must reproduce fp32 'reg' numerics exactly even when
    the incoming features are bf16 (the mixed-precision case the knob exists
    for — reference forces fp32 at core/raft_stereo.py:92,95)."""
    f1, f2, coords = fmaps
    f1_bf = jnp.asarray(f1).astype(jnp.bfloat16)
    f2_bf = jnp.asarray(f2).astype(jnp.bfloat16)
    # The knob cannot undo the bf16 rounding of the features themselves, so
    # the golden value is fp32 'reg' compute ON the bf16-rounded features —
    # any backend that secretly keeps bf16 compute/storage fails the tight
    # tolerance (bf16 compute drifts ~1e-2 here).
    want = make_corr_fn(RaftStereoConfig(corr_backend="reg"),
                        f1_bf.astype(jnp.float32),
                        f2_bf.astype(jnp.float32))(jnp.asarray(coords))
    for backend in ("reg", "alt", "reg_fused"):
        got = make_corr_fn(
            RaftStereoConfig(corr_backend=backend, mixed_precision=True,
                             corr_fp32=True), f1_bf, f2_bf)(jnp.asarray(coords))
        assert got.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
