"""Observability layer 2 (ISSUE 4): span tracing, flight recorder, anomaly
watchdogs — and the acceptance guarantees: zero device-fetch overhead when
disabled, a Perfetto-valid Chrome trace from a sampled run, and an
injected-NaN run producing a debug bundle + ``anomaly`` event."""

import json
import logging
import os
import time

import jax
import numpy as np
import pytest

from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
from raft_stereo_tpu.telemetry import (ANOMALY_VERSION, AnomalySink,
                                       EventLog, FlightRecorder,
                                       MetricsRegistry, NonFiniteSentinel,
                                       SpanTracer, StepStallWatchdog,
                                       TrainTelemetry, dump_all_stacks,
                                       escape_label_value, replay,
                                       to_chrome_trace,
                                       unescape_label_value)


# ------------------------------------------------------------ span tracer
def test_tracer_disabled_is_noop():
    t = SpanTracer(0.0)
    assert not t.enabled
    assert t.start_trace("x") is None
    with t.span("y") as s:
        assert s is None
    assert t.start_span("z", None) is None
    assert t.add_span("w", None, 0.0, 1.0) is None
    assert t.spans() == []


def test_tracer_span_tree_and_nesting():
    t = SpanTracer(1.0)
    tr = t.start_trace("root", kind="test")
    assert tr is not None and tr.root is not None
    with t.span("outer", tr) as outer:
        with t.span("inner", tr) as inner:
            assert inner.parent_id == outer.span_id
        assert outer.parent_id == tr.root.span_id
    t.finish_trace(tr)
    spans = {s.name: s for s in t.spans()}
    assert set(spans) == {"root", "outer", "inner"}
    assert spans["root"].attrs["kind"] == "test"
    assert all(s.trace_id == tr.trace_id for s in spans.values())
    assert spans["root"].t_end >= spans["root"].t_start


def test_tracer_sampling_rate_and_ring_bound():
    t = SpanTracer(0.5, ring=8, seed=7)
    traces = [t.start_trace("r") for _ in range(200)]
    sampled = [tr for tr in traces if tr is not None]
    # seeded rng: deterministic, and a 0.5 rate lands well inside (25, 175)
    assert 25 < len(sampled) < 175
    for tr in sampled:
        t.finish_trace(tr)
    assert len(t.spans()) <= 8      # ring bound holds
    stats = t.stats()
    assert stats["traces_started"] == 200
    assert stats["traces_sampled"] == len(sampled)
    with pytest.raises(ValueError):
        SpanTracer(1.5)


def test_chrome_trace_export_is_valid_and_complete():
    t = SpanTracer(1.0)
    tr = t.start_trace("req", bucket="(64, 64)")
    s = t.start_span("queue", tr, batch_size=3)
    time.sleep(0.002)
    t.finish(s)
    t.finish_trace(tr)
    out = to_chrome_trace(t.spans())
    parsed = json.loads(json.dumps(out))   # valid JSON round trip
    events = parsed["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"req", "queue"}
    for e in xs:
        assert e["ts"] > 0 and e["dur"] >= 0
        assert e["args"]["trace_id"] == tr.trace_id
    queue = next(e for e in xs if e["name"] == "queue")
    assert queue["args"]["parent_id"] == tr.root.span_id
    assert queue["args"]["batch_size"] == 3
    # metadata rows name the process and each thread lane
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in events)
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in events)


# -------------------------------------------- registry escaping (satellite)
def test_exposition_escapes_label_values_and_help():
    nasty = 'back\\slash "quote"\nnewline'
    reg = MetricsRegistry()
    reg.counter("c_total", "help with \\ and\nnewline",
                labels={"dev": nasty}).inc(2)
    reg.histogram("h_seconds", "h", buckets=(1.0,),
                  labels={"k": nasty}).observe(0.5)
    text = reg.render_text()
    # no raw newline may survive inside any single exposition line
    for line in text.splitlines():
        assert "\n" not in line
    sample = next(l for l in text.splitlines() if l.startswith("c_total{"))
    start = sample.index('dev="') + len('dev="')
    end = sample.rindex('"')
    assert unescape_label_value(sample[start:end]) == nasty  # round trip
    assert r"\n" in text and r"\\" in text
    # histogram: constant labels merge with le on every bucket line
    assert 'le="1"' in text and 'le="+Inf"' in text
    bucket_line = next(l for l in text.splitlines()
                       if l.startswith("h_seconds_bucket"))
    assert 'k="' in bucket_line and 'le="' in bucket_line


def test_escape_label_value_round_trip_edge_cases():
    for v in ("", "\\", '"', "\n", "\\n", '\\"', 'a\\b"c\nd', "\\\\\n\""):
        assert unescape_label_value(escape_label_value(v)) == v


def test_histogram_exemplars_bounded():
    from raft_stereo_tpu.telemetry.registry import EXEMPLAR_RING
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "l", buckets=(1.0,))
    h.observe(0.5)                       # no exemplar
    for i in range(EXEMPLAR_RING + 5):
        h.observe(0.1 * i, exemplar=f"trace{i}")
    ex = h.exemplars()
    assert len(ex) == EXEMPLAR_RING      # bounded ring
    assert ex[-1]["trace_id"] == f"trace{EXEMPLAR_RING + 4}"
    assert ex[-1]["value"] == pytest.approx(0.1 * (EXEMPLAR_RING + 4))


# ------------------------------------------------ torn-tail replay warning
def test_replay_warns_on_torn_tail_and_midfile_corruption(tmp_path, caplog):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as ev:
        ev.emit("run_start", name="x")
        ev.emit("step_stats", step=1)
    with open(path, "a") as f:
        f.write('{"event": "torn')       # SIGKILL mid-write, no newline
    with caplog.at_level(logging.WARNING,
                         logger="raft_stereo_tpu.telemetry.events"):
        recs = list(replay(path))
    assert [r["event"] for r in recs] == ["run_start", "step_stats"]
    assert "torn final line" in caplog.text
    caplog.clear()

    # mid-file corruption: the earlier records AND the later ones survive
    with open(path, "w") as f:
        f.write(json.dumps({"event": "a"}) + "\n")
        f.write("<<corrupt>>\n")
        f.write(json.dumps({"event": "b"}) + "\n")
    with caplog.at_level(logging.WARNING,
                         logger="raft_stereo_tpu.telemetry.events"):
        recs = list(replay(path))
    assert [r["event"] for r in recs] == ["a", "b"]
    assert "mid-file corruption" in caplog.text


# --------------------------------------------------------- flight recorder
def test_flight_recorder_bundle_contents(tmp_path):
    tracer = SpanTracer(1.0)
    tr = tracer.start_trace("req")
    tracer.finish_trace(tr)
    reg = MetricsRegistry()
    reg.counter("x_total", "t").inc(3)
    rec = FlightRecorder(str(tmp_path / "fr"), tracer=tracer, registry=reg,
                         min_interval_s=0.0)
    rec.record_event({"event": "step_stats", "step": 1})
    bundle = rec.dump("test_trigger", detail={"why": "unit test"})
    assert bundle is not None
    names = set(os.listdir(bundle))
    assert {"manifest.json", "trace.json", "spans.jsonl", "events.jsonl",
            "metrics.prom", "stacks.txt", "device_memory.json"} <= names
    manifest = json.load(open(os.path.join(bundle, "manifest.json")))
    assert manifest["trigger"] == "test_trigger"
    assert manifest["detail"]["why"] == "unit test"
    assert manifest["n_spans"] == 1 and manifest["n_events"] == 1
    trace = json.load(open(os.path.join(bundle, "trace.json")))
    assert any(e.get("name") == "req" for e in trace["traceEvents"])
    with open(os.path.join(bundle, "events.jsonl")) as f:
        evs = [json.loads(l) for l in f]
    assert evs[0]["event"] == "step_stats"
    assert "x_total 3" in open(os.path.join(bundle, "metrics.prom")).read()
    stacks = open(os.path.join(bundle, "stacks.txt")).read()
    assert "MainThread" in stacks and "test_flight_recorder" in stacks
    status = rec.status()
    assert status["dumps"] == 1 and status["bundles"] == [bundle]


def test_flight_recorder_rate_limit(tmp_path):
    rec = FlightRecorder(str(tmp_path / "fr"), min_interval_s=60.0)
    assert rec.dump("first") is not None
    assert rec.dump("second") is None            # suppressed
    assert rec.dump("forced", force=True) is not None
    assert rec.status()["dumps"] == 2


def test_dump_all_stacks_sees_all_threads():
    import threading

    done = threading.Event()
    t = threading.Thread(target=done.wait, name="stackdump-probe",
                         daemon=True)
    t.start()
    try:
        out = dump_all_stacks()
        assert "stackdump-probe" in out
        assert "MainThread" in out
    finally:
        done.set()


# -------------------------------------------------------------- watchdogs
def test_nonfinite_sentinel_rearms_after_recovery(tmp_path):
    events = EventLog(str(tmp_path / "e.jsonl"))
    rec = FlightRecorder(str(tmp_path / "fr"), min_interval_s=0.0)
    sink = AnomalySink(events=events, recorder=rec)
    s = NonFiniteSentinel(sink)
    assert s.check({"loss": float("nan"), "epe": 1.0}, step=3)
    assert not s.check({"loss": float("nan")}, step=4)   # latched
    assert not s.check({"loss": 0.5}, step=5)            # recovery re-arms
    assert s.check({"loss": float("inf")}, step=6)
    events.close()
    recs = [r for r in replay(events.path) if r["event"] == "anomaly"]
    assert len(recs) == 2
    assert recs[0]["anomaly_version"] == ANOMALY_VERSION
    assert recs[0]["kind"] == "non_finite_metric"
    assert recs[0]["step"] == 3 and "loss" in recs[0]["metrics"]
    assert recs[0]["bundle"] is not None
    assert sink.anomalies == 2


def test_step_stall_watchdog_fires_on_stall():
    sink = AnomalySink()
    wd = StepStallWatchdog(sink, factor=1.0, min_stall_s=0.05)
    assert not wd.check()                # no baseline yet -> silent
    wd.note_step(1)
    assert not wd.check()                # still no interval
    wd.note_step(2)                      # first interval (~0) -> floor rules
    assert wd.threshold_s() == pytest.approx(0.05)
    time.sleep(0.1)
    assert wd.check()                    # stalled past the floor
    assert not wd.check()                # latched until progress
    wd.note_step(3)                      # progress re-arms
    assert not wd.check()
    time.sleep(0.25)                     # median is now ~0.1s
    assert wd.check()
    assert sink.anomalies == 2


def test_serving_watchdog_detectors():
    from raft_stereo_tpu.serving.metrics import ServingMetrics
    from raft_stereo_tpu.telemetry import ServingWatchdog

    m = ServingMetrics()
    sink = AnomalySink(counter=m.anomalies)
    wd = ServingWatchdog(sink, m, max_queue=10, saturation=0.8,
                         sustain_s=0.02, miss_rate=0.5, min_events=4)
    # queue saturation must SUSTAIN before firing
    m.queue_depth.set(9)
    assert wd.check() == []
    time.sleep(0.03)
    assert wd.check() == ["queue_saturation"]
    assert wd.check() == []              # latched
    m.queue_depth.set(1)
    assert wd.check() == []              # clears + re-arms
    # deadline-miss rate over a poll window
    m.admitted.inc(10)
    m.deadline_missed.inc(6)
    assert wd.check() == ["deadline_miss_rate"]
    m.admitted.inc(10)
    m.deadline_missed.inc(6)
    assert wd.check() == []              # latched while rate stays high
    m.admitted.inc(10)                   # healthy window re-arms
    assert wd.check() == []
    m.admitted.inc(10)
    m.deadline_missed.inc(9)
    assert wd.check() == ["deadline_miss_rate"]
    assert m.anomalies.value == 3


# ---------------------------------------- instrumented runs (CPU, 5 steps)
class _SyntheticDataset:
    """Tiny synthetic stereo batches; ``nan_from`` poisons the flow GT of
    later items so the loss goes non-finite mid-run (the injected-NaN
    acceptance scenario)."""

    def __init__(self, nan_from=None):
        self.nan_from = nan_from

    def __len__(self):
        return 4

    def __getitem__(self, i, epoch=0):
        img = np.full((32, 64, 3), float(i), np.float32)
        flow = np.full((32, 64), -2.0, np.float32)
        if self.nan_from is not None and i >= self.nan_from:
            flow[:] = np.nan
        return {"image1": img, "image2": img, "flow": flow,
                "valid": np.ones((32, 64), np.float32)}


def _run_train(tmp_path, telemetry_obj, num_steps=5, train_iters=2,
               nan_from=None):
    from raft_stereo_tpu.data.loader import StereoLoader
    from raft_stereo_tpu.training.train_loop import train

    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), fnet_dim=64,
                            fnet_norm="none")
    tcfg = TrainConfig(batch_size=2, train_iters=train_iters,
                       num_steps=num_steps, image_size=(32, 64),
                       validation_frequency=10_000, data_parallel=1,
                       gru_telemetry=False)
    loader = StereoLoader(_SyntheticDataset(nan_from=nan_from), batch_size=2,
                          num_workers=0, shuffle=False)
    return train(mcfg, tcfg, name="obs", checkpoint_dir=str(tmp_path / "ck"),
                 log_dir=str(tmp_path / "runs"), loader=loader,
                 use_mesh=False, telemetry=telemetry_obj)


@pytest.fixture(scope="module")
def nan_run(tmp_path_factory):
    """ONE fully-instrumented injected-NaN run, sampling 1.0: the flight
    recorder, watchdog, and span assertions below share it."""
    tmp_path = tmp_path_factory.mktemp("nan_run")
    events = EventLog(str(tmp_path / "events.jsonl"))
    tracer = SpanTracer(1.0)
    recorder = FlightRecorder(str(tmp_path / "fr"), tracer=tracer,
                              min_interval_s=0.0)
    tm = TrainTelemetry(events=events, tracer=tracer, recorder=recorder)
    recorder.registry = tm.registry
    state = _run_train(tmp_path, tm, num_steps=5, nan_from=2)
    events.close()
    return dict(state=state, telemetry=tm, tracer=tracer, recorder=recorder,
                events_path=events.path)


def test_injected_nan_produces_bundle_and_anomaly_event(nan_run):
    """Acceptance: a non-finite loss on CPU produces a flight-recorder
    bundle plus an ``anomaly`` event in the run-event log."""
    recs = list(replay(nan_run["events_path"]))
    anomalies = [r for r in recs if r["event"] == "anomaly"]
    assert anomalies, "injected NaN must emit an anomaly event"
    a = anomalies[0]
    assert a["kind"] == "non_finite_metric"
    assert a["anomaly_version"] == ANOMALY_VERSION
    assert "loss" in a["metrics"]
    assert a["bundle"] is not None and os.path.isdir(a["bundle"])
    # event ordering stays coherent around the anomaly
    kinds = [r["event"] for r in recs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    assert nan_run["telemetry"].anomalies.value >= 1
    assert nan_run["telemetry"].healthz()["anomalies"] >= 1


def test_nan_run_bundle_replays_and_trace_parses(nan_run):
    """Satellite: the bundle's span ring replays cleanly and its Chrome
    trace JSON parses."""
    bundle = nan_run["recorder"].bundles[0]
    trace = json.load(open(os.path.join(bundle, "trace.json")))
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert "train.step" in names
    assert {"train.data_wait", "train.dispatch"} <= names
    with open(os.path.join(bundle, "spans.jsonl")) as f:
        spans = [json.loads(l) for l in f]
    assert spans and all(
        {"name", "trace_id", "span_id", "start_us", "duration_us"}
        <= set(s) for s in spans)
    # events ring replay: the same records the event log holds
    with open(os.path.join(bundle, "events.jsonl")) as f:
        evs = [json.loads(l) for l in f]
    assert evs[0]["event"] == "run_start"
    assert "metrics.prom" in os.listdir(bundle)
    assert "train_steps_total" in open(
        os.path.join(bundle, "metrics.prom")).read()


def test_train_step_span_trees_are_complete(nan_run):
    """Sampling 1.0: every step contributes a step trace whose data-wait /
    dispatch children parent to the step root, plus drain + checkpoint
    spans on the final step's trace."""
    spans = nan_run["tracer"].spans()
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    assert len(by_name["train.step"]) == 5
    assert len(by_name["train.data_wait"]) == 5
    assert len(by_name["train.dispatch"]) == 5
    assert by_name.get("train.metric_drain"), "final drain must be spanned"
    assert by_name.get("train.checkpoint"), "checkpoint must be spanned"
    roots = {s.span_id: s for s in by_name["train.step"]}
    for child in by_name["train.data_wait"] + by_name["train.dispatch"]:
        root = roots[child.parent_id]
        assert root.trace_id == child.trace_id
        assert root.t_start <= child.t_start + 1e-6
        assert child.t_end <= root.t_end + 1e-6
    # steps are distinct traces
    assert len({s.trace_id for s in by_name["train.step"]}) == 5
    # exemplars link the latency histograms back to these traces
    ex = nan_run["telemetry"].step_time.exemplars()
    assert ex and all(e["trace_id"] in {s.trace_id for s in spans}
                      for e in ex)


def test_spans_sampling_zero_adds_no_device_fetches(tmp_path, monkeypatch):
    """Acceptance: the train loop with telemetry + spans wired at sampling
    0 issues EXACTLY the ``jax.device_get`` calls the fully-disabled loop
    issues — the PR 3 zero-overhead guarantee extends to the span layer."""
    real_device_get = jax.device_get
    counts = []

    def run_counting(telemetry_obj, sub):
        calls = [0]

        def counting_get(x):
            calls[0] += 1
            return real_device_get(x)

        monkeypatch.setattr(jax, "device_get", counting_get)
        try:
            _run_train(tmp_path / sub, telemetry_obj, num_steps=2,
                       train_iters=1)
        finally:
            monkeypatch.setattr(jax, "device_get", real_device_get)
        counts.append(calls[0])

    run_counting(None, "off")
    tm = TrainTelemetry(tracer=SpanTracer(0.0))
    run_counting(tm, "spans0")
    assert counts[0] == counts[1], counts
    assert tm.tracer.spans() == []       # sampling 0 recorded nothing
