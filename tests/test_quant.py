"""Int8 quantized inference tier tests (tier-1, CPU): the round-15
turbo path.

Headline pins (the ISSUE acceptance properties):

* ``quant="off"`` is BITWISE the pre-quant program — no int8 ops trace
  into either the fixed-depth scan or the early-exit while program, and
  the quality tier's outputs equal the raw config's outputs exactly.
* Calibration is deterministic: same pairs -> byte-identical scale
  record; the scale file round-trips and version/mode-checks.
* Quantized and base executables can never collide in the persistent
  disk cache (distinct content keys) or the compile-cost registry
  (distinct key labels with the ``quant=int8`` tail).
* The int8 correlation pyramid's fused-kernel path (interpret mode)
  matches the XLA dequant fallback — the backend-independence contract
  of the kernel family.
* The per-session context cache reuses/invalidates correctly and its
  reuse program is numerically identical to the plain warm program.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from raft_stereo_tpu.config import (REQUEST_TIERS, RaftStereoConfig,
                                    parse_tier)
from raft_stereo_tpu.quant import (calibrate, corr_scales,
                                   dequantize_variables, load_scales,
                                   quantize_array, quantize_variables,
                                   quantized_param_bytes, save_scales,
                                   tree_is_quantized)

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


def _pair(hw=(32, 48), seed=3):
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 255, hw + (3,), dtype=np.uint8)
    return left, np.roll(left, -3, axis=1)


# ------------------------------------------------------------- core quant
def test_quantize_array_per_channel_roundtrip():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(3, 3, 8, 16)).astype(np.float32) * \
        np.linspace(0.1, 10.0, 16, dtype=np.float32)  # per-channel ranges
    q, s = quantize_array(w)
    assert q.dtype == np.int8 and s.shape == (1, 1, 1, 16)
    # per-channel scales: each channel's error bounded by ITS half-step,
    # the whole point over a per-tensor scale (Wu et al. 2020 §4)
    err = np.abs(q.astype(np.float32) * s - w)
    assert np.all(err <= 0.5 * s + 1e-7)
    # all-zero channels reproduce exactly (scale 1, q 0)
    w[..., 3] = 0.0
    q, s = quantize_array(w)
    assert np.all(q[..., 3] == 0) and s[0, 0, 0, 3] == 1.0


def test_quantize_variables_scope_and_dequant(tiny_model):
    _, variables = tiny_model
    qvars = quantize_variables(variables)
    assert tree_is_quantized(qvars)
    # encoder kernels packed; the update block stays full precision
    p = qvars["params"]
    assert "q8" in p["fnet"]["trunk"]["conv1"]["kernel"]
    assert "q8" in p["cnet"]["trunk"]["conv1"]["kernel"]
    assert "q8" in p["context_zqr_conv0"]["kernel"]
    flat_ub = p["update_block"]
    assert not tree_is_quantized({"params": flat_ub})
    # biases/norms untouched
    assert np.asarray(
        p["fnet"]["trunk"]["conv1"]["bias"]).dtype == np.float32
    # structural inverse + bounded error
    dq = dequantize_variables(qvars)
    orig = np.asarray(variables["params"]["fnet"]["trunk"]["conv1"]
                      ["kernel"])
    back = np.asarray(dq["params"]["fnet"]["trunk"]["conv1"]["kernel"])
    assert back.shape == orig.shape
    assert np.max(np.abs(back - orig)) <= np.max(np.abs(orig)) / 127 + 1e-6
    acct = quantized_param_bytes(qvars)
    assert acct["int8"] > 0 and acct["scales"] > 0


def test_quant_config_validation():
    with pytest.raises(ValueError, match="quant="):
        RaftStereoConfig(**TINY, quant="fp8")
    with pytest.raises(ValueError, match="rows_shards"):
        RaftStereoConfig(**TINY, quant="int8", rows_shards=2)
    with pytest.raises(ValueError, match="quant_corr_scales"):
        RaftStereoConfig(**TINY, quant="int8", quant_corr_scales=(1.0,))
    cfg = RaftStereoConfig(**TINY, quant="int8",
                           quant_corr_scales=(.1, .2, .3, .4))
    assert cfg.from_json(cfg.to_json()) == cfg


def test_turbo_tier_preset_and_ladder():
    from raft_stereo_tpu.serving.resilience import cost_ladder

    turbo = REQUEST_TIERS["turbo"]
    # Turbo v2 (r22): the preset rides the int8 COMPUTE path; the r15
    # weights-only mode stays reachable through inline specs.
    assert turbo.quant == "int8_mxu" and turbo.exit_threshold_px > 0
    inline = parse_tier("fast8:0.1:2:int8")
    assert inline.quant == "int8" and inline.min_iters == 2
    inline_mxu = parse_tier("fast8m:0.1:2:int8_mxu")
    assert inline_mxu.quant == "int8_mxu" and inline_mxu.min_iters == 2
    with pytest.raises(ValueError, match="quant"):
        parse_tier("bad:0.1:2:fp8")
    tiers = [parse_tier(t) for t in
             ("interactive", "balanced", "quality", "turbo")]
    ladder = cost_ladder(tiers)
    assert ladder[0] == "turbo" and ladder[-1] == "quality"


# ------------------------------------------------------- quant-off bitwise
def _jaxpr_has_int8(fn, *avals):
    import jax

    jaxpr = jax.make_jaxpr(fn)(*avals)
    return "i8[" in str(jaxpr)


def test_quant_off_traces_no_int8_scan_and_early_exit(tiny_model):
    """The bitwise-off pin at the jaxpr level: with quant='off' neither
    the fixed-depth scan program nor the early-exit while program
    contains a single int8 op — the traced computation IS the pre-quant
    one.  With quant='int8' both carry int8 (the positive control)."""
    import jax.numpy as jnp

    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg, variables = tiny_model
    img = jnp.zeros((1, 32, 64, 3), jnp.uint8)
    for exit_px in (0.0, 0.05):
        base = dataclasses.replace(cfg, exit_threshold_px=exit_px)
        fwd = make_forward(RAFTStereo(base), 2, donate_images=False)
        assert not _jaxpr_has_int8(fwd, variables, img, img)
        qcfg = dataclasses.replace(base, quant="int8")
        qfwd = make_forward(RAFTStereo(qcfg), 2, donate_images=False)
        qvars = quantize_variables(variables)
        assert _jaxpr_has_int8(qfwd, qvars, img, img)


def test_quality_tier_apply_is_identity_program(tiny_model):
    """REQUEST_TIERS['quality'].apply (quant='off') on the base config
    yields the base config exactly — the engine's shared-executable
    normalization depends on this equality."""
    cfg, _ = tiny_model
    assert REQUEST_TIERS["quality"].apply(cfg) == dataclasses.replace(
        cfg, exit_threshold_px=0.0, exit_min_iters=1, exit_max_iters=None)


# ------------------------------------------------------------- calibration
def test_calibration_deterministic_and_roundtrip(tiny_model, tmp_path):
    cfg, variables = tiny_model
    left, right = _pair()
    pairs = [(left, right), _pair(seed=7)]
    rec_a = calibrate(cfg, variables, pairs, percentile=99.5)
    rec_b = calibrate(cfg, variables, pairs, percentile=99.5)
    assert json.dumps(rec_a, sort_keys=True) == \
        json.dumps(rec_b, sort_keys=True)
    assert len(rec_a["corr_levels"]) == cfg.corr_levels
    assert rec_a["n_pairs"] == 2 and rec_a["activations"]
    # different data -> different scales (the record measures the input)
    rec_c = calibrate(cfg, variables, [_pair(seed=99)], percentile=99.5)
    assert rec_c["corr_levels"] != rec_a["corr_levels"]
    # file round trip + guards
    path = os.path.join(tmp_path, "scales.json")
    save_scales(path, rec_a)
    loaded = load_scales(path)
    assert loaded["corr_levels"] == rec_a["corr_levels"]
    scales = corr_scales(loaded)
    assert len(scales) == cfg.corr_levels and all(s > 0 for s in scales)
    bad = dict(rec_a, version=999)
    save_scales(path, bad)
    with pytest.raises(ValueError, match="version"):
        load_scales(path)


# ----------------------------------------------------------- int8 kernels
def test_int8_pyramid_fused_matches_xla_fallback():
    """Interpret-mode kernel parity: the fused int8 lookup (in-register
    dequant, scale applied after) equals the XLA fallback (dequant then
    sample) up to float associativity — same int8 grid either way."""
    import jax.numpy as jnp

    import raft_stereo_tpu.kernels.corr_lookup as cl
    from raft_stereo_tpu.models.corr import make_corr_fn

    rng = np.random.default_rng(1)
    b, h, w, d = 1, 8, 128, 32
    f1 = jnp.asarray(rng.normal(size=(b, h, w, d)).astype(np.float32))
    f2 = jnp.asarray(rng.normal(size=(b, h, w, d)).astype(np.float32))
    coords = jnp.asarray(
        rng.uniform(0, w, size=(b, h, w)).astype(np.float32))
    base = RaftStereoConfig(**TINY)
    old = cl._interpret_override
    try:
        for backend in ("reg_fused", "alt"):
            qcfg = dataclasses.replace(base, corr_backend=backend,
                                       quant="int8")
            cl._interpret_override = False     # XLA fallback path
            ref = make_corr_fn(qcfg, f1, f2)(coords)
            cl._interpret_override = True      # fused interpret kernels
            fused = make_corr_fn(qcfg, f1, f2)(coords)
            np.testing.assert_allclose(np.asarray(fused),
                                       np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)
    finally:
        cl._interpret_override = old


def test_int8_pyramid_calibrated_scales_clip():
    """Calibrated (percentile-clipped) scales saturate outliers at
    127*scale instead of blowing up the grid — the clip semantics the
    PTQ literature prescribes."""
    import jax.numpy as jnp

    from raft_stereo_tpu.models.corr import quantize_pyramid

    cfg = RaftStereoConfig(**TINY, quant="int8",
                           quant_corr_scales=(0.01,) * 4)
    vol = jnp.asarray(np.array([[[[0.5, -3.0, 0.002]]]], np.float32))
    qs, scales = quantize_pyramid([vol] * 4, cfg)
    q0 = np.asarray(qs[0])
    assert q0[0, 0, 0, 0] == 50          # 0.5 / 0.01
    assert q0[0, 0, 0, 1] == -127        # clipped
    assert float(scales[0]) == pytest.approx(0.01)


# --------------------------------------------------- runner / engine tier
def test_runner_int8_close_to_fp32(tiny_model):
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    left, right = _pair()
    r_fp = InferenceRunner(cfg, variables, iters=2)
    r_q = InferenceRunner(cfg, variables, iters=2, quant="int8")
    assert tree_is_quantized(r_q.variables)
    f_fp, _ = r_fp(left, right)
    f_q, _ = r_q(left, right)
    assert np.isfinite(f_q).all() and f_q.shape == f_fp.shape
    # loose: random-init nets amplify perturbations; the trained-weights
    # accuracy gate lives in tools/quant_drift.py
    denom = max(np.abs(f_fp).mean(), 1.0)
    assert np.abs(f_q - f_fp).mean() / denom < 0.5


def test_persist_keys_never_collide(tiny_model):
    """The acceptance pin: quantized and base executables get distinct
    persistent-cache AND compile-cost keys at every (bucket, batch) —
    exactly like the r14 warm/state family split."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=1, batch_sizes=(1,), iters=2,
        tiers=("turbo", "interactive", "quality"),
        default_tier="quality"))
    try:
        keys = {}
        cost_keys = {}
        for tier in (None, "turbo", "interactive"):
            ct = svc._cache_tier(tier)
            keys[tier] = svc._disk_key((32, 64), 1, 0, ct)
            cost_keys[tier] = svc._cost_key((32, 64), 1, tier)
        assert len(set(keys.values())) == 3, keys
        assert "quant=int8" in cost_keys["turbo"]
        assert "quant" not in cost_keys[None]
        assert "quant" not in cost_keys["interactive"]
        # family split keys stay distinct too (regression: r14 pin)
        k_base = svc._disk_key((32, 64), 1, 0, "turbo", family=None)
        k_state = svc._disk_key((32, 64), 1, 0, "turbo", family="state")
        assert k_base != k_state
    finally:
        svc.close()


def test_engine_turbo_tier_end_to_end(tiny_model):
    """One engine, quality + turbo: turbo runs the int8 program (close
    but not equal to quality), quality stays bitwise the solo fp32
    runner, and the two tiers compile distinct cost records."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    solo = InferenceRunner(cfg, variables, iters=2,
                           donate_images=False)
    solo_flow, _ = solo(left, right)
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=1, batch_sizes=(1,), iters=2, cost_telemetry=True,
        tiers=("turbo", "quality"), default_tier="quality"))
    try:
        r_q = svc.infer(left, right, tier="quality", timeout=300)
        r_t = svc.infer(left, right, tier="turbo", timeout=300)
        assert np.array_equal(r_q.flow, solo_flow), \
            "quality tier must stay bitwise the solo fp32 program"
        assert r_t.tier == "turbo"
        assert not np.array_equal(r_t.flow, r_q.flow)
        denom = max(np.abs(r_q.flow).mean(), 1.0)
        assert np.abs(r_t.flow - r_q.flow).mean() / denom < 0.5
        recs = {r.key for r in svc.costs.records()}
        assert any("quant=int8" in k for k in recs), recs
        assert any("quant" not in k for k in recs), recs
    finally:
        svc.close()


# ------------------------------------------------------ session ctx cache
def test_ctx_cache_config_validation(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    with pytest.raises(ValueError, match="sessions"):
        ServeConfig(session_ctx_cache=True)
    cfg, variables = tiny_model
    shared = dataclasses.replace(cfg, shared_backbone=True,
                                 n_downsample=3, n_gru_layers=2)
    with pytest.raises(ValueError, match="shared_backbone"):
        StereoService(shared, variables, ServeConfig(
            sessions=True, session_ctx_cache=True))


def test_ctx_reuse_program_matches_plain_warm(tiny_model):
    """The warm_ctx program fed the bundle a cold state_ctx frame saved
    produces EXACTLY the plain warm program's output: skipping the
    context encoder is a pure compute-reuse, not an approximation."""
    import jax.numpy as jnp

    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg, variables = tiny_model
    model = RAFTStereo(cfg)
    left, right = _pair()
    p1 = jnp.asarray(np.pad(left, ((0, 0), (0, 16), (0, 0)),
                            mode="edge")[None])
    p2 = jnp.asarray(np.pad(right, ((0, 0), (0, 16), (0, 0)),
                            mode="edge")[None])
    fwd_save = make_forward(model, 2, return_state=True, ctx="save",
                            donate_images=False)
    flow_up0, flow_low0, ctx = fwd_save(variables, p1, p2)
    # the ctx-saving cold program's flow equals the base program's
    fwd_base = make_forward(model, 2, donate_images=False)
    np.testing.assert_array_equal(np.asarray(flow_up0),
                                  np.asarray(fwd_base(variables, p1, p2)))
    fwd_warm = make_forward(model, 2, warm_start=True,
                            donate_images=False)
    fwd_reuse = make_forward(model, 2, warm_start=True, ctx="reuse",
                             donate_images=False)
    out_warm = fwd_warm(variables, p1, p2, flow_low0)
    out_reuse = fwd_reuse(variables, p1, p2, flow_low0, ctx)
    np.testing.assert_array_equal(np.asarray(out_reuse[0]),
                                  np.asarray(out_warm[0]))


def test_engine_session_ctx_cache_hits_and_invalidation(tiny_model):
    """Static-camera stream: frame 0 cold (bundle saved), later frames
    reuse it (X-Ctx-Cached semantics, counter, per-session stats); a
    frame past the static-scene gate drops the bundle; a scene cut
    recomputes it."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    bright = np.clip(left.astype(np.int32) + 30, 0, 255).astype(np.uint8)
    dark = (left * 0.2).astype(np.uint8)
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=1, batch_sizes=(1,), iters=2,
        sessions=True, session_ttl_s=600.0,
        session_ctx_cache=True, ctx_cache_threshold=3.0,
        scene_cut_threshold=40.0))
    try:
        r0 = svc.infer_session("s", left, right, timeout=300)
        assert not r0.warm and not r0.ctx_cached and r0.ctx is not None
        r1 = svc.infer_session("s", left, right, timeout=300)
        assert r1.warm and r1.ctx_cached
        r2 = svc.infer_session("s", left, right, timeout=300)
        assert r2.warm and r2.ctx_cached
        assert svc.metrics.ctx_cache_hits.value == 2
        # moderate delta: warm WITHOUT ctx (> gate, < scene cut) and the
        # bundle is invalidated — the next small-delta frame cannot hit
        r3 = svc.infer_session("s", bright, right, timeout=300)
        assert r3.warm and not r3.ctx_cached and not r3.scene_cut
        r4 = svc.infer_session("s", bright, right, timeout=300)
        assert r4.warm and not r4.ctx_cached, \
            "stale bundle must not be reused after an over-gate frame"
        # hard scene cut: cold start, bundle recomputed -> next frame hits
        r5 = svc.infer_session("s", dark, right, timeout=300)
        assert r5.scene_cut and not r5.warm
        r6 = svc.infer_session("s", dark, right, timeout=300)
        assert r6.warm and r6.ctx_cached
        stats = svc.close_session("s")
        assert stats["ctx_cache_hits"] == 3
        assert svc.metrics.ctx_cache_hits.value == 3
    finally:
        svc.close()


def test_ctx_cache_http_header(tiny_model):
    """X-Ctx-Cached rides the stream response exactly when the frame
    reused the bundle."""
    import io
    import urllib.request

    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    left, right = _pair()
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=1, batch_sizes=(1,), iters=2,
        sessions=True, session_ttl_s=600.0,
        session_ctx_cache=True, ctx_cache_threshold=3.0))
    server = StereoHTTPServer(svc, port=0).start()
    try:
        def post(sid):
            buf = io.BytesIO()
            np.savez(buf, left=left, right=right)
            req = urllib.request.Request(
                f"{server.url}/v1/stream/{sid}", data=buf.getvalue(),
                method="POST",
                headers={"Content-Type": "application/x-npz"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                return dict(resp.headers)
        h0 = post("cam")
        h1 = post("cam")
        assert "X-Ctx-Cached" not in h0 and h0["X-Warm"] == "0"
        assert h1.get("X-Ctx-Cached") == "1" and h1["X-Warm"] == "1"
    finally:
        server.shutdown()
        svc.close()


# ------------------------------------------------ quantized compute (r22)
def test_ascale_pack_is_quantized_leaf():
    """Pack detection accepts both key sets: {q8, qscale} (r15) and
    {q8, qscale, ascale} (r22 calibrated activation scales) — and
    rejects partial dicts, so a corrupt tree can never half-route."""
    from raft_stereo_tpu.quant import is_quantized_leaf

    q8 = np.zeros((3, 3, 4, 8), np.int8)
    qs = np.ones((1, 1, 1, 8), np.float32)
    assert is_quantized_leaf({"q8": q8, "qscale": qs})
    assert is_quantized_leaf({"q8": q8, "qscale": qs,
                              "ascale": np.float32(0.1)})
    assert not is_quantized_leaf({"q8": q8})
    assert not is_quantized_leaf({"q8": q8, "qscale": qs, "extra": 1})
    assert not is_quantized_leaf(np.zeros((3, 3, 4, 8), np.float32))


def test_quantconv_pack_matches_fp(tiny_model):
    """QuantConv routing is data-driven: the same module applied with
    the fp tree and with a {q8, qscale} pack tree agree within the
    int8 quantization budget, and the pack apply is finite."""
    import jax.numpy as jnp

    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg, variables = tiny_model
    model = RAFTStereo(cfg)
    im = jnp.asarray(_pair()[0][None].astype(np.float32))
    qvars = quantize_variables(variables)
    f_fp = np.asarray(model.apply(variables, im, im, iters=2,
                                  test_mode=True)[1])
    f_q = np.asarray(model.apply(qvars, im, im, iters=2,
                                 test_mode=True)[1])
    assert np.isfinite(f_q).all() and f_q.shape == f_fp.shape
    # loose on random init — the trained-weights gate is quant_drift's
    denom = max(np.abs(f_fp).mean(), 1.0)
    assert np.abs(f_q - f_fp).mean() / denom < 0.5


def test_int8_mxu_jaxpr_pin(tiny_model):
    """The r22 acceptance pin: quant='int8_mxu' traces >= 1 int8 x int8
    -> int32 conv with NO fp32 dequant feeding any matmul (quantized
    compute, not dequantize-then-fp32), in both the fixed-depth scan
    and the early-exit while program.  quant='off' keeps its zero-
    int8-matmul twin of the existing bitwise pin."""
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.quant import int8_matmul_report

    cfg, variables = tiny_model
    img = jnp.zeros((1, 32, 64, 3), jnp.uint8)
    qvars = quantize_variables(variables)
    for exit_px in (0.0, 0.05):
        base = dataclasses.replace(cfg, exit_threshold_px=exit_px)
        mxu = dataclasses.replace(base, quant="int8_mxu")
        fwd = make_forward(RAFTStereo(mxu), 2, donate_images=False)
        rep = int8_matmul_report(jax.make_jaxpr(fwd)(qvars, img, img))
        assert rep["int8_convs"] + rep["int8_dots"] >= 1, rep
        assert rep["dequant_fed_matmuls"] == 0, rep
        off = make_forward(RAFTStereo(base), 2, donate_images=False)
        rep_off = int8_matmul_report(
            jax.make_jaxpr(off)(variables, img, img))
        assert rep_off["int8_convs"] + rep_off["int8_dots"] == 0, rep_off


def test_conv_input_scales_mapping(tiny_model):
    """conv_input_scales maps the calibration record's sown ``qin``
    sites back to PARAM-TREE paths (the act_scales contract of
    quantize_variables), and the mapped scales ride the packs as
    ``ascale`` — absent exactly where calibration has no coverage."""
    from raft_stereo_tpu.quant import conv_input_scales

    cfg, variables = tiny_model
    rec = calibrate(cfg, variables, [_pair(), _pair(seed=7)])
    scales = conv_input_scales(rec)
    assert scales and all(s > 0 for s in scales.values())
    params = variables["params"]
    for path in scales:
        node = params
        for part in path.split("/"):
            assert part in node, f"unresolvable scale path {path!r}"
            node = node[part]
        assert "kernel" in node, path
    assert "fnet/trunk/conv1" in scales
    # context_zqr convs sit outside the calibration capture surface:
    # they take the dynamic in-graph fallback, never a stale ascale
    assert not any(p.startswith("context_zqr") for p in scales)
    qvars = quantize_variables(variables, act_scales=scales)
    p = qvars["params"]
    covered = p["fnet"]["trunk"]["conv1"]["kernel"]
    uncovered = p["context_zqr_conv0"]["kernel"]
    assert "ascale" in covered and float(covered["ascale"]) == \
        pytest.approx(scales["fnet/trunk/conv1"])
    assert "q8" in uncovered and "ascale" not in uncovered
    # pre-r22 records (no activations section) degrade to {}
    assert conv_input_scales({"activations": {}}) == {}


def test_fp8_corr_capability_gate():
    """fp8 q-entries are capability-gated: unavailable on plain CPU
    (corr_q_dtype transparently falls back to int8 so
    ``quant_corr_fp8=True`` is safe everywhere), available under the
    interpret override, and check_q_dtype rejects an fp8 pyramid
    whenever the gate says no."""
    import jax.numpy as jnp

    import raft_stereo_tpu.kernels.corr_lookup as cl
    from raft_stereo_tpu.models.corr import corr_q_dtype

    if cl.FP8_CORR_DTYPE is None:
        pytest.skip("this jax build has no float8_e4m3fn dtype")
    cfg = RaftStereoConfig(**TINY, quant="int8", quant_corr_fp8=True)
    old = cl._interpret_override
    try:
        cl._interpret_override = False
        assert not cl.fp8_corr_available()
        assert jnp.dtype(corr_q_dtype(cfg)) == jnp.dtype(jnp.int8)
        fp8_lvl = jnp.zeros((1, 4, 8, 8), cl.FP8_CORR_DTYPE)
        with pytest.raises(ValueError, match="fp8"):
            cl.check_q_dtype([fp8_lvl], None)
        cl._interpret_override = True
        assert cl.fp8_corr_available()
        assert jnp.dtype(corr_q_dtype(cfg)) == \
            jnp.dtype(cl.FP8_CORR_DTYPE)
        assert cl.check_q_dtype([fp8_lvl], None) == \
            jnp.dtype(cl.FP8_CORR_DTYPE)
    finally:
        cl._interpret_override = old
    # mixed-dtype pyramids are rejected regardless of capability
    with pytest.raises(ValueError, match="levels"):
        cl.check_q_dtype([jnp.zeros((1, 4, 8, 8), jnp.int8),
                          jnp.zeros((1, 4, 8, 4), jnp.float32)], jnp.int8)


def test_fp8_pyramid_lookup_parity_interpret():
    """Kernel-level fp8 parity in interpret mode: the q entry sampling
    an fp8 grid equals the fp fused kernel sampling the SAME grid
    upcast to fp32 — the kernel body is dtype-generic, the in-register
    upcast is the only difference."""
    import jax.numpy as jnp

    import raft_stereo_tpu.kernels.corr_lookup as cl
    from raft_stereo_tpu.quant.core import FP8_QMAX, quantize_fp8

    if cl.FP8_CORR_DTYPE is None:
        pytest.skip("this jax build has no float8_e4m3fn dtype")
    rng = np.random.default_rng(2)
    b, h, w1, radius = 1, 4, 32, 3
    pyramid_f32 = [
        jnp.asarray(rng.normal(size=(b, h, w1, w2)).astype(np.float32))
        for w2 in (32, 16, 8)]
    coords = jnp.asarray(
        rng.uniform(0, w1, size=(b, h, w1)).astype(np.float32))
    old = cl._interpret_override
    try:
        cl._interpret_override = True
        pyramid_q = []
        for lvl in pyramid_f32:
            scale = float(np.abs(np.asarray(lvl)).max()) / FP8_QMAX
            pyramid_q.append(quantize_fp8(lvl, scale, cl.FP8_CORR_DTYPE))
        got = cl.lookup_pyramid_fused_q(pyramid_q, coords, radius,
                                        out_dtype=jnp.float32)
        ref = cl.lookup_pyramid_fused(
            [q.astype(jnp.float32) for q in pyramid_q], coords, radius)
        assert jnp.isfinite(got).all()
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        cl._interpret_override = old
