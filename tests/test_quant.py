"""Int8 quantized inference tier tests (tier-1, CPU): the round-15
turbo path.

Headline pins (the ISSUE acceptance properties):

* ``quant="off"`` is BITWISE the pre-quant program — no int8 ops trace
  into either the fixed-depth scan or the early-exit while program, and
  the quality tier's outputs equal the raw config's outputs exactly.
* Calibration is deterministic: same pairs -> byte-identical scale
  record; the scale file round-trips and version/mode-checks.
* Quantized and base executables can never collide in the persistent
  disk cache (distinct content keys) or the compile-cost registry
  (distinct key labels with the ``quant=int8`` tail).
* The int8 correlation pyramid's fused-kernel path (interpret mode)
  matches the XLA dequant fallback — the backend-independence contract
  of the kernel family.
* The per-session context cache reuses/invalidates correctly and its
  reuse program is numerically identical to the plain warm program.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from raft_stereo_tpu.config import (REQUEST_TIERS, RaftStereoConfig,
                                    parse_tier)
from raft_stereo_tpu.quant import (calibrate, corr_scales,
                                   dequantize_variables, load_scales,
                                   quantize_array, quantize_variables,
                                   quantized_param_bytes, save_scales,
                                   tree_is_quantized)

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


def _pair(hw=(32, 48), seed=3):
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 255, hw + (3,), dtype=np.uint8)
    return left, np.roll(left, -3, axis=1)


# ------------------------------------------------------------- core quant
def test_quantize_array_per_channel_roundtrip():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(3, 3, 8, 16)).astype(np.float32) * \
        np.linspace(0.1, 10.0, 16, dtype=np.float32)  # per-channel ranges
    q, s = quantize_array(w)
    assert q.dtype == np.int8 and s.shape == (1, 1, 1, 16)
    # per-channel scales: each channel's error bounded by ITS half-step,
    # the whole point over a per-tensor scale (Wu et al. 2020 §4)
    err = np.abs(q.astype(np.float32) * s - w)
    assert np.all(err <= 0.5 * s + 1e-7)
    # all-zero channels reproduce exactly (scale 1, q 0)
    w[..., 3] = 0.0
    q, s = quantize_array(w)
    assert np.all(q[..., 3] == 0) and s[0, 0, 0, 3] == 1.0


def test_quantize_variables_scope_and_dequant(tiny_model):
    _, variables = tiny_model
    qvars = quantize_variables(variables)
    assert tree_is_quantized(qvars)
    # encoder kernels packed; the update block stays full precision
    p = qvars["params"]
    assert "q8" in p["fnet"]["trunk"]["conv1"]["kernel"]
    assert "q8" in p["cnet"]["trunk"]["conv1"]["kernel"]
    assert "q8" in p["context_zqr_conv0"]["kernel"]
    flat_ub = p["update_block"]
    assert not tree_is_quantized({"params": flat_ub})
    # biases/norms untouched
    assert np.asarray(
        p["fnet"]["trunk"]["conv1"]["bias"]).dtype == np.float32
    # structural inverse + bounded error
    dq = dequantize_variables(qvars)
    orig = np.asarray(variables["params"]["fnet"]["trunk"]["conv1"]
                      ["kernel"])
    back = np.asarray(dq["params"]["fnet"]["trunk"]["conv1"]["kernel"])
    assert back.shape == orig.shape
    assert np.max(np.abs(back - orig)) <= np.max(np.abs(orig)) / 127 + 1e-6
    acct = quantized_param_bytes(qvars)
    assert acct["int8"] > 0 and acct["scales"] > 0


def test_quant_config_validation():
    with pytest.raises(ValueError, match="quant="):
        RaftStereoConfig(**TINY, quant="fp8")
    with pytest.raises(ValueError, match="rows_shards"):
        RaftStereoConfig(**TINY, quant="int8", rows_shards=2)
    with pytest.raises(ValueError, match="quant_corr_scales"):
        RaftStereoConfig(**TINY, quant="int8", quant_corr_scales=(1.0,))
    cfg = RaftStereoConfig(**TINY, quant="int8",
                           quant_corr_scales=(.1, .2, .3, .4))
    assert cfg.from_json(cfg.to_json()) == cfg


def test_turbo_tier_preset_and_ladder():
    from raft_stereo_tpu.serving.resilience import cost_ladder

    turbo = REQUEST_TIERS["turbo"]
    assert turbo.quant == "int8" and turbo.exit_threshold_px > 0
    inline = parse_tier("fast8:0.1:2:int8")
    assert inline.quant == "int8" and inline.min_iters == 2
    with pytest.raises(ValueError, match="quant"):
        parse_tier("bad:0.1:2:fp8")
    tiers = [parse_tier(t) for t in
             ("interactive", "balanced", "quality", "turbo")]
    ladder = cost_ladder(tiers)
    assert ladder[0] == "turbo" and ladder[-1] == "quality"


# ------------------------------------------------------- quant-off bitwise
def _jaxpr_has_int8(fn, *avals):
    import jax

    jaxpr = jax.make_jaxpr(fn)(*avals)
    return "i8[" in str(jaxpr)


def test_quant_off_traces_no_int8_scan_and_early_exit(tiny_model):
    """The bitwise-off pin at the jaxpr level: with quant='off' neither
    the fixed-depth scan program nor the early-exit while program
    contains a single int8 op — the traced computation IS the pre-quant
    one.  With quant='int8' both carry int8 (the positive control)."""
    import jax.numpy as jnp

    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg, variables = tiny_model
    img = jnp.zeros((1, 32, 64, 3), jnp.uint8)
    for exit_px in (0.0, 0.05):
        base = dataclasses.replace(cfg, exit_threshold_px=exit_px)
        fwd = make_forward(RAFTStereo(base), 2, donate_images=False)
        assert not _jaxpr_has_int8(fwd, variables, img, img)
        qcfg = dataclasses.replace(base, quant="int8")
        qfwd = make_forward(RAFTStereo(qcfg), 2, donate_images=False)
        qvars = quantize_variables(variables)
        assert _jaxpr_has_int8(qfwd, qvars, img, img)


def test_quality_tier_apply_is_identity_program(tiny_model):
    """REQUEST_TIERS['quality'].apply (quant='off') on the base config
    yields the base config exactly — the engine's shared-executable
    normalization depends on this equality."""
    cfg, _ = tiny_model
    assert REQUEST_TIERS["quality"].apply(cfg) == dataclasses.replace(
        cfg, exit_threshold_px=0.0, exit_min_iters=1, exit_max_iters=None)


# ------------------------------------------------------------- calibration
def test_calibration_deterministic_and_roundtrip(tiny_model, tmp_path):
    cfg, variables = tiny_model
    left, right = _pair()
    pairs = [(left, right), _pair(seed=7)]
    rec_a = calibrate(cfg, variables, pairs, percentile=99.5)
    rec_b = calibrate(cfg, variables, pairs, percentile=99.5)
    assert json.dumps(rec_a, sort_keys=True) == \
        json.dumps(rec_b, sort_keys=True)
    assert len(rec_a["corr_levels"]) == cfg.corr_levels
    assert rec_a["n_pairs"] == 2 and rec_a["activations"]
    # different data -> different scales (the record measures the input)
    rec_c = calibrate(cfg, variables, [_pair(seed=99)], percentile=99.5)
    assert rec_c["corr_levels"] != rec_a["corr_levels"]
    # file round trip + guards
    path = os.path.join(tmp_path, "scales.json")
    save_scales(path, rec_a)
    loaded = load_scales(path)
    assert loaded["corr_levels"] == rec_a["corr_levels"]
    scales = corr_scales(loaded)
    assert len(scales) == cfg.corr_levels and all(s > 0 for s in scales)
    bad = dict(rec_a, version=999)
    save_scales(path, bad)
    with pytest.raises(ValueError, match="version"):
        load_scales(path)


# ----------------------------------------------------------- int8 kernels
def test_int8_pyramid_fused_matches_xla_fallback():
    """Interpret-mode kernel parity: the fused int8 lookup (in-register
    dequant, scale applied after) equals the XLA fallback (dequant then
    sample) up to float associativity — same int8 grid either way."""
    import jax.numpy as jnp

    import raft_stereo_tpu.kernels.corr_lookup as cl
    from raft_stereo_tpu.models.corr import make_corr_fn

    rng = np.random.default_rng(1)
    b, h, w, d = 1, 8, 128, 32
    f1 = jnp.asarray(rng.normal(size=(b, h, w, d)).astype(np.float32))
    f2 = jnp.asarray(rng.normal(size=(b, h, w, d)).astype(np.float32))
    coords = jnp.asarray(
        rng.uniform(0, w, size=(b, h, w)).astype(np.float32))
    base = RaftStereoConfig(**TINY)
    old = cl._interpret_override
    try:
        for backend in ("reg_fused", "alt"):
            qcfg = dataclasses.replace(base, corr_backend=backend,
                                       quant="int8")
            cl._interpret_override = False     # XLA fallback path
            ref = make_corr_fn(qcfg, f1, f2)(coords)
            cl._interpret_override = True      # fused interpret kernels
            fused = make_corr_fn(qcfg, f1, f2)(coords)
            np.testing.assert_allclose(np.asarray(fused),
                                       np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)
    finally:
        cl._interpret_override = old


def test_int8_pyramid_calibrated_scales_clip():
    """Calibrated (percentile-clipped) scales saturate outliers at
    127*scale instead of blowing up the grid — the clip semantics the
    PTQ literature prescribes."""
    import jax.numpy as jnp

    from raft_stereo_tpu.models.corr import quantize_pyramid

    cfg = RaftStereoConfig(**TINY, quant="int8",
                           quant_corr_scales=(0.01,) * 4)
    vol = jnp.asarray(np.array([[[[0.5, -3.0, 0.002]]]], np.float32))
    qs, scales = quantize_pyramid([vol] * 4, cfg)
    q0 = np.asarray(qs[0])
    assert q0[0, 0, 0, 0] == 50          # 0.5 / 0.01
    assert q0[0, 0, 0, 1] == -127        # clipped
    assert float(scales[0]) == pytest.approx(0.01)


# --------------------------------------------------- runner / engine tier
def test_runner_int8_close_to_fp32(tiny_model):
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    left, right = _pair()
    r_fp = InferenceRunner(cfg, variables, iters=2)
    r_q = InferenceRunner(cfg, variables, iters=2, quant="int8")
    assert tree_is_quantized(r_q.variables)
    f_fp, _ = r_fp(left, right)
    f_q, _ = r_q(left, right)
    assert np.isfinite(f_q).all() and f_q.shape == f_fp.shape
    # loose: random-init nets amplify perturbations; the trained-weights
    # accuracy gate lives in tools/quant_drift.py
    denom = max(np.abs(f_fp).mean(), 1.0)
    assert np.abs(f_q - f_fp).mean() / denom < 0.5


def test_persist_keys_never_collide(tiny_model):
    """The acceptance pin: quantized and base executables get distinct
    persistent-cache AND compile-cost keys at every (bucket, batch) —
    exactly like the r14 warm/state family split."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=1, batch_sizes=(1,), iters=2,
        tiers=("turbo", "interactive", "quality"),
        default_tier="quality"))
    try:
        keys = {}
        cost_keys = {}
        for tier in (None, "turbo", "interactive"):
            ct = svc._cache_tier(tier)
            keys[tier] = svc._disk_key((32, 64), 1, 0, ct)
            cost_keys[tier] = svc._cost_key((32, 64), 1, tier)
        assert len(set(keys.values())) == 3, keys
        assert "quant=int8" in cost_keys["turbo"]
        assert "quant" not in cost_keys[None]
        assert "quant" not in cost_keys["interactive"]
        # family split keys stay distinct too (regression: r14 pin)
        k_base = svc._disk_key((32, 64), 1, 0, "turbo", family=None)
        k_state = svc._disk_key((32, 64), 1, 0, "turbo", family="state")
        assert k_base != k_state
    finally:
        svc.close()


def test_engine_turbo_tier_end_to_end(tiny_model):
    """One engine, quality + turbo: turbo runs the int8 program (close
    but not equal to quality), quality stays bitwise the solo fp32
    runner, and the two tiers compile distinct cost records."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    solo = InferenceRunner(cfg, variables, iters=2,
                           donate_images=False)
    solo_flow, _ = solo(left, right)
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=1, batch_sizes=(1,), iters=2, cost_telemetry=True,
        tiers=("turbo", "quality"), default_tier="quality"))
    try:
        r_q = svc.infer(left, right, tier="quality", timeout=300)
        r_t = svc.infer(left, right, tier="turbo", timeout=300)
        assert np.array_equal(r_q.flow, solo_flow), \
            "quality tier must stay bitwise the solo fp32 program"
        assert r_t.tier == "turbo"
        assert not np.array_equal(r_t.flow, r_q.flow)
        denom = max(np.abs(r_q.flow).mean(), 1.0)
        assert np.abs(r_t.flow - r_q.flow).mean() / denom < 0.5
        recs = {r.key for r in svc.costs.records()}
        assert any("quant=int8" in k for k in recs), recs
        assert any("quant" not in k for k in recs), recs
    finally:
        svc.close()


# ------------------------------------------------------ session ctx cache
def test_ctx_cache_config_validation(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    with pytest.raises(ValueError, match="sessions"):
        ServeConfig(session_ctx_cache=True)
    cfg, variables = tiny_model
    shared = dataclasses.replace(cfg, shared_backbone=True,
                                 n_downsample=3, n_gru_layers=2)
    with pytest.raises(ValueError, match="shared_backbone"):
        StereoService(shared, variables, ServeConfig(
            sessions=True, session_ctx_cache=True))


def test_ctx_reuse_program_matches_plain_warm(tiny_model):
    """The warm_ctx program fed the bundle a cold state_ctx frame saved
    produces EXACTLY the plain warm program's output: skipping the
    context encoder is a pure compute-reuse, not an approximation."""
    import jax.numpy as jnp

    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg, variables = tiny_model
    model = RAFTStereo(cfg)
    left, right = _pair()
    p1 = jnp.asarray(np.pad(left, ((0, 0), (0, 16), (0, 0)),
                            mode="edge")[None])
    p2 = jnp.asarray(np.pad(right, ((0, 0), (0, 16), (0, 0)),
                            mode="edge")[None])
    fwd_save = make_forward(model, 2, return_state=True, ctx="save",
                            donate_images=False)
    flow_up0, flow_low0, ctx = fwd_save(variables, p1, p2)
    # the ctx-saving cold program's flow equals the base program's
    fwd_base = make_forward(model, 2, donate_images=False)
    np.testing.assert_array_equal(np.asarray(flow_up0),
                                  np.asarray(fwd_base(variables, p1, p2)))
    fwd_warm = make_forward(model, 2, warm_start=True,
                            donate_images=False)
    fwd_reuse = make_forward(model, 2, warm_start=True, ctx="reuse",
                             donate_images=False)
    out_warm = fwd_warm(variables, p1, p2, flow_low0)
    out_reuse = fwd_reuse(variables, p1, p2, flow_low0, ctx)
    np.testing.assert_array_equal(np.asarray(out_reuse[0]),
                                  np.asarray(out_warm[0]))


def test_engine_session_ctx_cache_hits_and_invalidation(tiny_model):
    """Static-camera stream: frame 0 cold (bundle saved), later frames
    reuse it (X-Ctx-Cached semantics, counter, per-session stats); a
    frame past the static-scene gate drops the bundle; a scene cut
    recomputes it."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    left, right = _pair()
    bright = np.clip(left.astype(np.int32) + 30, 0, 255).astype(np.uint8)
    dark = (left * 0.2).astype(np.uint8)
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=1, batch_sizes=(1,), iters=2,
        sessions=True, session_ttl_s=600.0,
        session_ctx_cache=True, ctx_cache_threshold=3.0,
        scene_cut_threshold=40.0))
    try:
        r0 = svc.infer_session("s", left, right, timeout=300)
        assert not r0.warm and not r0.ctx_cached and r0.ctx is not None
        r1 = svc.infer_session("s", left, right, timeout=300)
        assert r1.warm and r1.ctx_cached
        r2 = svc.infer_session("s", left, right, timeout=300)
        assert r2.warm and r2.ctx_cached
        assert svc.metrics.ctx_cache_hits.value == 2
        # moderate delta: warm WITHOUT ctx (> gate, < scene cut) and the
        # bundle is invalidated — the next small-delta frame cannot hit
        r3 = svc.infer_session("s", bright, right, timeout=300)
        assert r3.warm and not r3.ctx_cached and not r3.scene_cut
        r4 = svc.infer_session("s", bright, right, timeout=300)
        assert r4.warm and not r4.ctx_cached, \
            "stale bundle must not be reused after an over-gate frame"
        # hard scene cut: cold start, bundle recomputed -> next frame hits
        r5 = svc.infer_session("s", dark, right, timeout=300)
        assert r5.scene_cut and not r5.warm
        r6 = svc.infer_session("s", dark, right, timeout=300)
        assert r6.warm and r6.ctx_cached
        stats = svc.close_session("s")
        assert stats["ctx_cache_hits"] == 3
        assert svc.metrics.ctx_cache_hits.value == 3
    finally:
        svc.close()


def test_ctx_cache_http_header(tiny_model):
    """X-Ctx-Cached rides the stream response exactly when the frame
    reused the bundle."""
    import io
    import urllib.request

    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    left, right = _pair()
    svc = StereoService(cfg, variables, ServeConfig(
        max_batch=1, batch_sizes=(1,), iters=2,
        sessions=True, session_ttl_s=600.0,
        session_ctx_cache=True, ctx_cache_threshold=3.0))
    server = StereoHTTPServer(svc, port=0).start()
    try:
        def post(sid):
            buf = io.BytesIO()
            np.savez(buf, left=left, right=right)
            req = urllib.request.Request(
                f"{server.url}/v1/stream/{sid}", data=buf.getvalue(),
                method="POST",
                headers={"Content-Type": "application/x-npz"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                return dict(resp.headers)
        h0 = post("cam")
        h1 = post("cam")
        assert "X-Ctx-Cached" not in h0 and h0["X-Warm"] == "0"
        assert h1.get("X-Ctx-Cached") == "1" and h1["X-Warm"] == "1"
    finally:
        server.shutdown()
        svc.close()
