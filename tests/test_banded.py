"""Banded trunk (models/banded.py) vs the ordinary _Trunk: identical math,
band-sized memory.  Heights exercise non-multiple-of-band and odd sizes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.models.banded import banded_trunk_apply
from raft_stereo_tpu.models.extractor import _Trunk


@pytest.mark.parametrize("norm_fn", ["instance", "batch", "none"])
@pytest.mark.parametrize("h,w,band", [(64, 96, 32), (70, 96, 32)])
def test_banded_matches_trunk(rng, norm_fn, h, w, band):
    trunk = _Trunk(norm_fn, downsample=2, dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (2, h, w, 3)), jnp.float32)
    variables = trunk.init(jax.random.PRNGKey(0), x)
    want = trunk.apply(variables, x)

    got = banded_trunk_apply(variables["params"],
                             variables.get("batch_stats", {}),
                             x, norm_fn, jnp.float32, band=band)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("norm_fn", ["instance", "batch"])
def test_banded_gradients_match_trunk(rng, norm_fn):
    """jax.grad through the banded trunk equals grad through the plain
    trunk — the checkpoint/lax.map machinery in banded_trunk_apply exists
    for training at full resolution, so its VJP must match, not just its
    forward (VERDICT round 2 weak #4)."""
    trunk = _Trunk(norm_fn, downsample=2, dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (2, 70, 64, 3)), jnp.float32)
    variables = trunk.init(jax.random.PRNGKey(0), x)
    params = variables["params"]
    bs = variables.get("batch_stats", {})
    # A non-uniform cotangent so reduction-order bugs can't cancel out.
    probe = None

    def loss_plain(p, x):
        out = trunk.apply({"params": p, "batch_stats": bs}, x)
        return jnp.sum(out * probe)

    def loss_banded(p, x):
        out = banded_trunk_apply(p, bs, x, norm_fn, jnp.float32, band=32)
        return jnp.sum(out * probe)

    out_shape = jax.eval_shape(lambda: trunk.apply(variables, x)).shape
    probe = jnp.asarray(rng.standard_normal(out_shape), jnp.float32)

    gp_params, gp_x = jax.grad(loss_plain, argnums=(0, 1))(params, x)
    gb_params, gb_x = jax.grad(loss_banded, argnums=(0, 1))(params, x)

    flat_p = jax.tree_util.tree_leaves_with_path(gp_params)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(gb_params))
    assert len(flat_p) == len(flat_b)
    # Absolute tolerance scaled by the OVERALL gradient magnitude: per-band
    # partial sums reassociate the fp32 reductions, so leaves that are
    # mathematically ~0 (e.g. a pre-instance-norm conv bias, whose shift the
    # mean subtraction cancels exactly) hold noise proportional to the
    # global gradient scale, not their own.  Structural VJP bugs produce
    # O(1)-relative errors on the large leaves, which this still catches.
    gmax = max(float(np.abs(leaf).max()) for _, leaf in flat_p)
    atol = 1e-4 * gmax

    def check(got, want, name):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=atol, err_msg=name)

    check(gb_x, gp_x, "d/dx")
    for path, leaf in flat_p:
        check(flat_b[path], leaf, jax.tree_util.keystr(path))


@pytest.mark.slow
def test_banded_model_matches_plain(rng):
    """Full model with banded_encoder=True vs the plain model — same params,
    near-identical disparity (only fp reassociation of the instance-norm
    stats differs, amplified ~5x/iter by the untrained GRU)."""
    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    img1 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    cfg = RaftStereoConfig(n_gru_layers=2, hidden_dims=(48, 48))
    model = RAFTStereo(cfg)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                   test_mode=True)
    _, up_ref = model.apply(v, img1, img2, iters=3, test_mode=True)

    import dataclasses
    cfg_b = dataclasses.replace(cfg, banded_encoder=True)
    model_b = RAFTStereo(cfg_b)
    _, up_b = jax.jit(
        lambda v, a, b: model_b.apply(v, a, b, iters=3, test_mode=True)
    )(v, img1, img2)
    np.testing.assert_allclose(np.asarray(up_b), np.asarray(up_ref),
                               rtol=1e-3, atol=5e-3)


@pytest.mark.slow
def test_banded_model_shared_backbone(rng):
    """Banded trunk under the shared-backbone (realtime-style, batch-norm
    cnet) path; ds2 to stay in banded-supported range."""
    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    img1 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    cfg = RaftStereoConfig(n_gru_layers=2, hidden_dims=(48, 48),
                           shared_backbone=True, n_downsample=2)
    model = RAFTStereo(cfg)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                   test_mode=True)
    _, up_ref = model.apply(v, img1, img2, iters=3, test_mode=True)

    import dataclasses
    cfg_b = dataclasses.replace(cfg, banded_encoder=True)
    _, up_b = RAFTStereo(cfg_b).apply(v, img1, img2, iters=3, test_mode=True)
    np.testing.assert_allclose(np.asarray(up_b), np.asarray(up_ref),
                               rtol=1e-3, atol=5e-3)
