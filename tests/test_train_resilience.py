"""Divergence-proof training (round 20): anomaly policy units, loader
fault isolation, checkpoint integrity/retention, prefetcher crash
semantics.

The quick tier here is deliberately host-side (no model compiles): the
policy/tracker logic, the loader's quarantine + exact-resume state
machine, the checkpoint manifest byte-flip property sweep (on a tiny
synthetic tree — satellite 2), and the _DevicePrefetcher terminal-state
fix (satellite 1).  The jitted-step gate and the full rewind/preempt
loop run in the slow tier and, end to end with injected faults, in
scripts/train_smoke.py (CI) / tools/train_chaos.py (the chaos matrix).
"""

import json
import os

import numpy as np
import pytest

from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
from raft_stereo_tpu.data.loader import StereoLoader
from raft_stereo_tpu.training import checkpoint as ckpt
from raft_stereo_tpu.training.anomaly import (AnomalyPolicy, AnomalyTracker,
                                              TrainingDiverged)


# ------------------------------------------------------------ policy units
def test_anomaly_policy_validation():
    with pytest.raises(ValueError, match="spike_factor"):
        AnomalyPolicy(spike_factor=-1.0)
    with pytest.raises(ValueError, match="ewma_beta"):
        AnomalyPolicy(ewma_beta=1.0)
    with pytest.raises(ValueError, match="rewind_after"):
        AnomalyPolicy(rewind_after=-1)
    with pytest.raises(ValueError, match="max_rewinds"):
        AnomalyPolicy(max_rewinds=-1)


def test_anomaly_policy_from_train_config():
    assert AnomalyPolicy.from_train_config(TrainConfig()) is None
    p = AnomalyPolicy.from_train_config(TrainConfig(
        anomaly_policy=True, anomaly_spike_factor=5.0,
        anomaly_rewind_after=2, anomaly_max_rewinds=1))
    assert p == AnomalyPolicy(spike_factor=5.0, ewma_beta=0.98,
                              rewind_after=2, max_rewinds=1)


def test_tracker_consecutive_counting_and_rewind_arming():
    t = AnomalyTracker(AnomalyPolicy(rewind_after=3))
    assert t.observe(1, {"skipped": 0.0}) is None
    assert t.observe(2, {"skipped": 1.0, "skip_nonfinite": 1.0}) \
        == "nonfinite"
    assert t.observe(3, {"skipped": 1.0, "skip_nonfinite": 0.0,
                         "skip_spike": 1.0}) == "spike"
    assert not t.should_rewind()         # 2 consecutive < 3
    assert t.observe(4, {"skipped": 0.0}) is None
    assert t.consecutive == 0            # a clean step re-arms
    for s in (5, 6, 7):
        t.observe(s, {"skipped": 1.0, "skip_nonfinite": 1.0})
    assert t.should_rewind()
    t.note_rewind(7, 4, "/ck/4_run")
    assert not t.should_rewind() and t.rewinds == 1
    assert t.skipped_nonfinite == 4 and t.skipped_spike == 1


def test_tracker_history_roundtrip():
    t = AnomalyTracker(AnomalyPolicy(rewind_after=2, max_rewinds=3))
    for s in (1, 2):
        t.observe(s, {"skipped": 1.0, "skip_nonfinite": 1.0})
    t.note_rewind(2, 0, "/ck/x")
    h = json.loads(json.dumps(t.history()))   # JSON round-trip like the blob
    t2 = AnomalyTracker(AnomalyPolicy(rewind_after=2, max_rewinds=3))
    t2.load_history(h)
    assert t2.rewinds == 1 and t2.skipped_nonfinite == 2
    assert t2.rewind_budget_left()
    t2.note_rewind(5, 3, "/ck/y")
    t2.note_rewind(9, 6, "/ck/z")
    assert not t2.rewind_budget_left()    # budget survives the round-trip


def test_training_diverged_is_typed():
    e = TrainingDiverged(123, "out of rewinds")
    assert e.step == 123 and "out of rewinds" in str(e)
    assert isinstance(e, RuntimeError)


# ------------------------------------------------- loader fault isolation
class _FaultDataset:
    """Deterministic samples; ``bad`` raise always, ``flaky`` raise on
    the first decode only."""

    def __init__(self, n=8, bad=(), flaky=()):
        self.n = n
        self.bad = set(bad)
        self.flaky = dict.fromkeys(flaky, 0)

    def __len__(self):
        return self.n

    def __getitem__(self, i, epoch=0):
        if i in self.bad:
            raise ValueError(f"corrupt sample {i}")
        if i in self.flaky and self.flaky[i] == 0:
            self.flaky[i] += 1
            raise ValueError(f"flaky sample {i}")
        return {"x": np.full((2, 2), float(i) + 100.0 * epoch)}


def _values(loader):
    return [sorted(b["x"][:, 0, 0].tolist()) for b in loader]


def test_loader_quarantines_raising_sample_and_substitutes(tmp_path):
    qp = str(tmp_path / "q.json")
    loader = StereoLoader(_FaultDataset(bad=(3,)), batch_size=2,
                          num_workers=0, shuffle=False, epochs=1,
                          quarantine_path=qp)
    vals = _values(loader)
    # sample 3's slot is filled by its deterministic substitute (4)
    assert vals == [[0.0, 1.0], [2.0, 4.0], [4.0, 5.0], [6.0, 7.0]]
    assert loader.stats["quarantined"] == 1 and loader.quarantined == {3}
    with open(qp) as f:
        payload = json.load(f)
    # round 21: content-hash keyed format (key None here — the test
    # dataset exposes no sample_paths, so index identity is the fallback)
    assert payload["version"] == 2
    assert [e["index"] for e in payload["samples"]] == [3]
    # a fresh loader starts from the persisted quarantine list
    loader2 = StereoLoader(_FaultDataset(bad=(3,)), batch_size=2,
                           num_workers=0, shuffle=False, epochs=1,
                           quarantine_path=qp)
    assert loader2.quarantined == {3}
    assert _values(loader2) == vals
    assert loader2.stats["quarantined"] == 0   # no NEW quarantine


def test_loader_quarantine_legacy_index_file_migrates(tmp_path):
    qp = str(tmp_path / "q.json")
    with open(qp, "w") as f:
        json.dump({"indices": [3]}, f)        # pre-round-21 format
    loader = StereoLoader(_FaultDataset(bad=(3,)), batch_size=2,
                          num_workers=0, shuffle=False, epochs=1,
                          quarantine_path=qp)
    assert loader.quarantined == {3}
    with open(qp) as f:                       # rewritten as v2 in place
        payload = json.load(f)
    assert payload["version"] == 2
    assert [e["index"] for e in payload["samples"]] == [3]


def test_loader_quarantine_content_key_survives_relisting(tmp_path):
    from raft_stereo_tpu.data.loader import sample_content_key

    class _FileDataset(_FaultDataset):
        """_FaultDataset with real file identity (sample_paths)."""

        def __init__(self, files, **kw):
            super().__init__(n=len(files), **kw)
            self.files = list(files)

        def sample_paths(self, i):
            return (self.files[i],)

    files = []
    for i in range(8):
        p = tmp_path / f"s{i}.bin"
        p.write_bytes(bytes([i]) * (i + 1))
        files.append(str(p))
    qp = str(tmp_path / "q.json")
    ds = _FileDataset(files, bad=(3,))
    loader = StereoLoader(ds, batch_size=2, num_workers=0, shuffle=False,
                          epochs=1, quarantine_path=qp)
    list(loader)
    assert loader.quarantined == {3}
    key3 = sample_content_key(ds, 3)
    with open(qp) as f:
        assert json.load(f)["samples"] == [{"index": 3, "key": key3}]
    # Re-list the dataset with a new file prepended: every index shifts
    # by one, but the content key re-locates the same bad file.
    extra = tmp_path / "s_new.bin"
    extra.write_bytes(b"xx" * 9)
    ds2 = _FileDataset([str(extra)] + files, bad=(4,))
    loader2 = StereoLoader(ds2, batch_size=2, num_workers=0,
                           shuffle=False, epochs=1, quarantine_path=qp)
    assert loader2.quarantined == {4}         # same file, new index
    # Replacing the bad file (different size) clears its quarantine.
    with open(files[3], "ab") as f:
        f.write(b"repaired")
    loader3 = StereoLoader(_FileDataset(files, bad=()), batch_size=2,
                           num_workers=0, shuffle=False, epochs=1,
                           quarantine_path=qp)
    assert loader3.quarantined == set()


def test_loader_retry_succeeds_without_quarantine():
    loader = StereoLoader(_FaultDataset(flaky=(5,)), batch_size=2,
                          num_workers=0, shuffle=False, epochs=1)
    vals = _values(loader)
    assert vals[2] == [4.0, 5.0]          # the flaky sample decoded
    assert loader.stats == {"retried": 1, "quarantined": 0,
                            "worker_respawns": 0}


def test_loader_threaded_matches_sync_under_faults():
    mk = lambda w: StereoLoader(_FaultDataset(bad=(3,)), batch_size=2,  # noqa: E731
                                num_workers=w, shuffle=False, epochs=1)
    assert _values(mk(3)) == _values(mk(0))


def test_loader_fault_isolation_off_propagates():
    loader = StereoLoader(_FaultDataset(bad=(3,)), batch_size=2,
                          num_workers=0, shuffle=False, epochs=1,
                          fault_isolation=False)
    with pytest.raises(ValueError, match="corrupt sample 3"):
        list(loader)


def test_loader_all_quarantined_is_typed():
    from raft_stereo_tpu.data.loader import LoaderBroken, _substitute_index
    with pytest.raises(LoaderBroken, match="quarantined"):
        _substitute_index(0, 4, {0, 1, 2, 3})


# ------------------------------------------------- loader exact-resume state
def test_loader_offset_resume_is_exact():
    mk = lambda: StereoLoader(_FaultDataset(16), batch_size=2,  # noqa: E731
                              num_workers=0, seed=7, epochs=2)
    full = [b["x"][:, 0, 0].tolist() for b in mk()]
    resumed = mk()
    resumed.set_state({"offset": 5, "salts": []})
    assert [b["x"][:, 0, 0].tolist() for b in resumed] == full[5:]


def test_loader_salt_reshuffles_remaining_epoch_only():
    mk = lambda: StereoLoader(_FaultDataset(16), batch_size=2,  # noqa: E731
                              num_workers=0, seed=7, epochs=1)
    base = [b["x"][:, 0, 0].tolist() for b in mk()]
    salted = mk()
    salted.set_state({"offset": 3, "salts": [[0, 3, 1]]})
    tail = [b["x"][:, 0, 0].tolist() for b in salted]
    flat_base = [v for b in base[3:] for v in b]
    flat_tail = [v for b in tail for v in b]
    # same sample set (no repeats, nothing lost), different order
    assert sorted(flat_base) == sorted(flat_tail)
    assert flat_base != flat_tail
    # salts apply with shuffle OFF too (that is the rewind's whole point)
    unshuffled = StereoLoader(_FaultDataset(16), batch_size=2,
                              num_workers=0, shuffle=False, epochs=1)
    plain = [b["x"][:, 0, 0].tolist() for b in unshuffled]
    unshuffled2 = StereoLoader(_FaultDataset(16), batch_size=2,
                               num_workers=0, shuffle=False, epochs=1)
    unshuffled2.add_salt(0, 0, 1)
    assert [b["x"][:, 0, 0].tolist() for b in unshuffled2] != plain


def test_loader_state_roundtrip_and_consumed_accounting():
    loader = StereoLoader(_FaultDataset(16), batch_size=2, num_workers=0,
                          seed=7, epochs=2)
    loader.set_state({"offset": 3, "salts": [[0, 3, 2]]})
    it = iter(loader)
    consumed = [next(it) for _ in range(4)]
    state = loader.state(consumed=4)
    assert state == {"offset": 7, "salts": [[0, 3, 2]]}
    twin = StereoLoader(_FaultDataset(16), batch_size=2, num_workers=0,
                        seed=7, epochs=2)
    twin.set_state(state)
    rest = [b["x"][:, 0, 0].tolist() for b in twin]
    tail = [b["x"][:, 0, 0].tolist() for b in it]
    assert rest == tail
    del consumed


@pytest.mark.slow
def test_loader_process_worker_respawn(tmp_path):
    """A SIGKILLed process worker (the OOM-kill case) is respawned and
    its in-flight batches resubmitted in order — the consumer sees every
    batch exactly once, plus a worker_respawns count."""
    import procworker_support as sup   # importable by spawn children

    marker = str(tmp_path / "killed.marker")
    loader = StereoLoader(sup.KillOnceDataset(marker, kill_index=5),
                          batch_size=2, num_workers=2, shuffle=False,
                          epochs=1, worker_type="process")
    vals = _values(loader)
    assert vals == [[0.0, 1.0], [2.0, 3.0], [4.0, 5.0], [6.0, 7.0]]
    assert loader.stats["worker_respawns"] >= 1
    assert os.path.exists(marker)


# --------------------------------------------------- prefetcher (satellite 1)
def test_prefetcher_reraises_and_stays_terminal():
    from raft_stereo_tpu.training.train_loop import _DevicePrefetcher

    def gen():
        yield 1
        yield 2
        raise RuntimeError("upload died")

    pf = _DevicePrefetcher(gen(), put=lambda x: x * 10, depth=1)
    assert next(pf) == 10 and next(pf) == 20
    with pytest.raises(RuntimeError, match="upload died"):
        next(pf)
    # the old bug: this second call blocked forever on the empty queue
    with pytest.raises(RuntimeError, match="upload died"):
        next(pf)
    pf.close(timeout=2.0)
    assert not pf._thread.is_alive()


def test_prefetcher_put_exception_surfaces():
    from raft_stereo_tpu.training.train_loop import _DevicePrefetcher

    def bad_put(x):
        raise ValueError("device_put failed")

    pf = _DevicePrefetcher(iter([1, 2, 3]), put=bad_put, depth=1)
    with pytest.raises(ValueError, match="device_put failed"):
        next(pf)
    with pytest.raises(ValueError, match="device_put failed"):
        next(pf)   # terminal, no hang
    pf.close(timeout=2.0)
    assert not pf._thread.is_alive()


def test_prefetcher_exhaustion_is_sticky_and_close_joins():
    from raft_stereo_tpu.training.train_loop import _DevicePrefetcher

    pf = _DevicePrefetcher(iter([1]), put=lambda x: x, depth=1)
    assert next(pf) == 1
    assert next(pf, None) is None
    assert next(pf, None) is None   # sticky StopIteration, no hang
    pf.close(timeout=2.0)
    assert not pf._thread.is_alive()


# ------------------------------------- checkpoint integrity (satellite 2)
def _tiny_tree(step=7, seed=0):
    rng = np.random.default_rng(seed)
    return {"params": {"w": rng.normal(size=(4, 3)).astype(np.float32),
                       "b": rng.normal(size=(3,)).astype(np.float32)},
            "batch_stats": {},
            "opt_state": {"mu": {"w": np.zeros((4, 3), np.float32)}},
            "step": np.asarray(step)}


def _save(tmp_path, name, step, runtime=None):
    path = str(tmp_path / f"{step}_{name}")
    ckpt.save_checkpoint(path, RaftStereoConfig(), _tiny_tree(step),
                         runtime_state=runtime)
    return path


def test_checkpoint_byte_flip_property_sweep(tmp_path):
    """Satellite 2 (the handoff-codec v2 pattern): flip a byte ANYWHERE
    in the newest checkpoint — deep validation must reject it and
    latest_checkpoint must fall back to the newest intact step, with a
    typed reject reason.  Never a crash, never garbage."""
    older = _save(tmp_path, "run", 7)
    newest = _save(tmp_path, "run", 9)
    rng = np.random.default_rng(11)
    flips = 0
    reasons = set()
    for root, _dirs, files in os.walk(newest):
        for fn in files:
            fp = os.path.join(root, fn)
            with open(fp, "rb") as f:
                blob = f.read()
            if not blob:
                continue
            pos = int(rng.integers(0, len(blob)))
            bad = bytearray(blob)
            bad[pos] ^= 0xFF
            with open(fp, "wb") as f:
                f.write(bytes(bad))
            flips += 1
            rej = []
            assert not ckpt.is_valid_checkpoint(newest, deep=True), \
                f"flip in {fn} at {pos} undetected"
            got = ckpt.latest_checkpoint(
                str(tmp_path), name="run", deep=True,
                on_reject=lambda p, r: rej.append(r))
            assert got == older, f"flip in {fn}: fell back to {got}"
            assert rej, "rejection must be typed"
            reasons.update(rej)
            with open(fp, "wb") as f:
                f.write(blob)
    assert flips >= 4            # config, runtime-less commit, manifest, state
    # intact again after the sweep restored every byte
    assert ckpt.is_valid_checkpoint(newest, deep=True)
    assert ckpt.latest_checkpoint(str(tmp_path), name="run",
                                  deep=True) == newest
    assert any(r.startswith(("hash_mismatch", "manifest", "commit"))
               for r in reasons)


def test_checkpoint_truncation_and_missing_file_detected(tmp_path):
    path = _save(tmp_path, "run", 5)
    manifest = json.load(open(os.path.join(path, ckpt.MANIFEST_FILE)))
    victim = os.path.join(path, sorted(manifest["files"])[-1])
    blob = open(victim, "rb").read()
    with open(victim, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert not ckpt.is_valid_checkpoint(path, deep=True)
    os.remove(victim)
    ok, reason = ckpt.verify_manifest(path)
    assert not ok and reason.startswith("missing_file:")


def test_checkpoint_runtime_sidecar_roundtrip(tmp_path):
    rt = {"loop_step": 7, "loader": {"offset": 7, "salts": [[0, 3, 1]]},
          "loss_ewma": 1.5, "anomaly": {"rewinds": 1}}
    path = _save(tmp_path, "run", 7, runtime=rt)
    assert ckpt.load_runtime_state(path) == rt
    # absent on checkpoints saved without one (legacy/weights-only)
    bare = str(tmp_path / "bare")
    ckpt.save_checkpoint(bare, RaftStereoConfig(), _tiny_tree(0))
    assert ckpt.load_runtime_state(bare) is None
    assert ckpt.is_valid_checkpoint(bare, deep=True)


def test_checkpoint_good_stamp_and_prune_retention(tmp_path):
    paths = {s: _save(tmp_path, "run", s) for s in (3, 5, 7, 9, 11)}
    ckpt.mark_good(paths[5])
    assert ckpt.is_good(paths[5]) and not ckpt.is_good(paths[9])
    # GOOD is advisory metadata outside the manifest seal: deep
    # validation still passes with the stamp present.
    assert ckpt.is_valid_checkpoint(paths[5], deep=True)
    removed = ckpt.prune_checkpoints(str(tmp_path), name="run", keep=2)
    left = sorted(os.listdir(tmp_path))
    assert "11_run" in left and "9_run" in left       # keep-last-2
    assert "5_run" in left                            # newest GOOD survives
    assert "3_run" not in left and "7_run" not in left
    assert sorted(os.path.basename(p) for p in removed) == ["3_run",
                                                            "7_run"]
    # keep=0 = retention off
    assert ckpt.prune_checkpoints(str(tmp_path), name="run", keep=0) == []


def test_valid_checkpoints_orders_newest_first(tmp_path):
    for s in (3, 9, 5):
        _save(tmp_path, "run", s)
    got = [os.path.basename(p)
           for p in ckpt.valid_checkpoints(str(tmp_path), name="run")]
    assert got == ["9_run", "5_run", "3_run"]


def test_legacy_checkpoint_without_manifest_still_validates(tmp_path):
    path = _save(tmp_path, "run", 5)
    os.remove(os.path.join(path, ckpt.MANIFEST_FILE))
    # pre-round-20 writer: COMMIT without a manifest seal
    with open(os.path.join(path, ckpt.COMMIT_FILE), "w") as f:
        json.dump({"complete": True, "step": 5}, f)
    assert ckpt.is_valid_checkpoint(path)
    assert ckpt.is_valid_checkpoint(path, deep=True)   # nothing to verify
    ok, reason = ckpt.verify_manifest(path)
    assert ok and reason == "legacy_no_manifest"
    # but a sealed COMMIT whose manifest vanished is torn, not legacy
    path2 = _save(tmp_path, "run", 7)
    os.remove(os.path.join(path2, ckpt.MANIFEST_FILE))
    assert not ckpt.is_valid_checkpoint(path2, deep=True)


# ------------------------------------------------- jitted-step gate (slow)
@pytest.mark.slow
def test_anomaly_step_skips_nonfinite_and_spike(rng):
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.training.state import create_train_state
    from raft_stereo_tpu.training.step import make_train_step

    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), fnet_dim=64,
                            corr_levels=2, corr_radius=3, fnet_norm="batch")
    tcfg = TrainConfig(train_iters=1, num_steps=100, anomaly_policy=True,
                       anomaly_spike_factor=8.0)
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               image_shape=(1, 32, 64, 3))
    policy = AnomalyPolicy.from_train_config(tcfg)
    step_fn = make_train_step(tcfg, donate=False, anomaly=policy)
    b, h, w = 2, 32, 64
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)),
                              jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)),
                              jnp.float32),
        "flow": jnp.asarray(rng.normal(0, 5, (b, h, w)), jnp.float32),
        "valid": jnp.ones((b, h, w), jnp.float32)}

    s1, m1, e1 = step_fn(state, batch, jnp.float32(0.0))
    assert float(m1["skipped"]) == 0.0 and float(e1) > 0
    assert int(s1.step) == 1

    nan_batch = dict(batch, flow=jnp.full((b, h, w), jnp.nan))
    s2, m2, e2 = step_fn(s1, nan_batch, e1)
    assert float(m2["skipped"]) == 1.0
    assert float(m2["skip_nonfinite"]) == 1.0
    assert float(e2) == float(e1)           # skipped loss never enters EWMA
    assert int(s2.step) == 1                # step counter untouched
    for a, b_ in zip(jax.tree_util.tree_leaves(s1.params),
                     jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    for a, b_ in zip(jax.tree_util.tree_leaves(s1.opt_state),
                     jax.tree_util.tree_leaves(s2.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))

    spike_batch = dict(batch, flow=jnp.asarray(
        np.sign(np.asarray(batch["flow"])) * 600.0, jnp.float32))
    s3, m3, e3 = step_fn(s2, spike_batch, e2)
    assert float(m3["skip_spike"]) == 1.0 and float(m3["skipped"]) == 1.0
    assert np.isfinite(float(m3["loss"]))   # finite — the gate, not NaN
    assert float(e3) == float(e2)
    for a, b_ in zip(jax.tree_util.tree_leaves(s2.params),
                     jax.tree_util.tree_leaves(s3.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))


@pytest.mark.slow
def test_anomaly_step_policy_off_signature_unchanged(rng):
    """policy=None keeps the exact two-arg, two-output step (the
    pre-round-20 program; existing suites pin its numerics)."""
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.training.state import create_train_state
    from raft_stereo_tpu.training.step import make_train_step

    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), fnet_dim=64,
                            corr_levels=2, corr_radius=3, fnet_norm="batch")
    tcfg = TrainConfig(train_iters=1, num_steps=100)
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               image_shape=(1, 32, 64, 3))
    step_fn = make_train_step(tcfg, donate=False)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (2, 32, 64, 3)),
                              jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (2, 32, 64, 3)),
                              jnp.float32),
        "flow": jnp.asarray(rng.normal(0, 5, (2, 32, 64)), jnp.float32),
        "valid": jnp.ones((2, 32, 64), jnp.float32)}
    out = step_fn(state, batch)
    assert len(out) == 2
    _, metrics = out
    assert "skipped" not in metrics
