"""Unified telemetry subsystem (raft_stereo_tpu/telemetry/): shared
registry, structured events, training instruments + endpoint, trace
capture, and the zero-overhead-when-disabled guarantee."""

import json
import os
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu import telemetry
from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
from raft_stereo_tpu.telemetry import (EventLog, TelemetryHTTPServer,
                                       TraceBusy, TraceCapture,
                                       TrainTelemetry, bench_record, replay,
                                       write_record)


# ------------------------------------------------------- registry promotion
def test_serving_metrics_reexports_shared_registry():
    """The serving imports keep working unchanged AND resolve to the one
    shared implementation in telemetry/registry.py."""
    from raft_stereo_tpu.serving import metrics as serving_metrics
    from raft_stereo_tpu.telemetry import registry as shared

    for name in ("Counter", "Gauge", "Histogram", "MetricsRegistry",
                 "DEFAULT_LATENCY_BUCKETS"):
        assert getattr(serving_metrics, name) is getattr(shared, name), name

    m = serving_metrics.ServingMetrics()
    text = m.render_text()
    assert "serve_requests_admitted_total" in text
    assert "serve_queue_wait_seconds_bucket" in text


# ------------------------------------------------------------------ events
def test_event_log_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as ev:
        ev.emit("run_start", name="x", step=0)
        ev.emit("step_stats", step=100, means={"loss": 1.5})
        ev.emit("run_end", status="complete", step=100)
    recs = list(replay(path))
    assert [r["event"] for r in recs] == ["run_start", "step_stats",
                                          "run_end"]
    assert [r["seq"] for r in recs] == [0, 1, 2]
    assert all(r["schema_version"] == telemetry.SCHEMA_VERSION for r in recs)
    assert recs[1]["means"]["loss"] == 1.5
    assert recs[0]["ts"] <= recs[2]["ts"]


def test_event_log_numpy_values_and_torn_tail(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as ev:
        ev.emit("step_stats", loss=np.float32(2.5),
                deltas=np.arange(3, dtype=np.float32))
    with open(path, "a") as f:
        f.write('{"event": "torn')  # SIGKILL mid-write
    recs = list(replay(path))
    assert len(recs) == 1
    assert recs[0]["loss"] == 2.5
    assert recs[0]["deltas"] == [0.0, 1.0, 2.0]


def test_bench_record_header_and_write(tmp_path):
    rec = bench_record({"metric": "m", "value": 1.25, "unit": "u"})
    assert rec["schema_version"] == telemetry.SCHEMA_VERSION
    assert rec["metric"] == "m" and rec["value"] == 1.25  # contract intact
    assert rec["run"]["platform"] == "cpu"
    assert rec["run"]["n_devices"] == len(jax.devices())
    json.dumps(rec)  # must be serializable as-is

    path = str(tmp_path / "BENCH.json")
    write_record(path, {"metric": "m2", "value": 2})
    with open(path) as f:
        back = json.load(f)
    assert back["schema_version"] == telemetry.SCHEMA_VERSION
    assert back["metric"] == "m2"
    # already-wrapped records are not double-wrapped
    write_record(path, rec)
    with open(path) as f:
        assert json.load(f)["run"] == rec["run"]


# ----------------------------------------------------------- trace capture
def test_trace_capture_bounded_window(tmp_path):
    cap = TraceCapture(root=str(tmp_path / "prof"))
    info = cap.start(duration_ms=telemetry.trace.MAX_TRACE_MS * 10)
    assert info["duration_ms"] == telemetry.trace.MAX_TRACE_MS  # clamped
    assert cap.active
    with pytest.raises(TraceBusy):
        cap.start()
    x = jnp.ones((32, 32))
    jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    assert cap.stop() is True
    assert cap.stop() is False  # idempotent
    found = [f for _, _, fs in os.walk(info["trace_dir"]) for f in fs]
    assert found, "trace capture produced no files"
    with pytest.raises(ValueError):
        cap.start(duration_ms=0)


# ------------------------------------------- the instrumented training run
class _SyntheticDataset:
    def __len__(self):
        return 4

    def __getitem__(self, i, epoch=0):
        img = np.full((32, 64, 3), float(i), np.float32)
        return {"image1": img, "image2": img,
                "flow": np.full((32, 64), -2.0, np.float32),
                "valid": np.ones((32, 64), np.float32)}


def _tiny_cfgs(num_steps=5, train_iters=2, gru_telemetry=True):
    # fnet_norm="none": InstanceNorm's optimization_barrier lacks a CPU
    # differentiation rule in this jax version.
    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), fnet_dim=64,
                            fnet_norm="none")
    tcfg = TrainConfig(batch_size=2, train_iters=train_iters,
                       num_steps=num_steps, image_size=(32, 64),
                       validation_frequency=10_000, data_parallel=1,
                       gru_telemetry=gru_telemetry)
    return mcfg, tcfg


def _run_train(tmp_path, telemetry_obj, num_steps=5, **cfg_kw):
    from raft_stereo_tpu.data.loader import StereoLoader
    from raft_stereo_tpu.training.train_loop import train

    mcfg, tcfg = _tiny_cfgs(num_steps=num_steps, **cfg_kw)
    loader = StereoLoader(_SyntheticDataset(), batch_size=2, num_workers=0,
                          shuffle=False)
    return train(mcfg, tcfg, name="tel", checkpoint_dir=str(tmp_path / "ck"),
                 log_dir=str(tmp_path / "runs"), loader=loader,
                 use_mesh=False, telemetry=telemetry_obj)


@pytest.fixture(scope="module")
def scraped_run(tmp_path_factory):
    """ONE instrumented 5-step CPU run with a live endpoint; the scrape
    results and event log are shared by the assertions below (the
    acceptance path: train --metrics_port is live-scrapable)."""
    tmp_path = tmp_path_factory.mktemp("telemetry_run")
    events = EventLog(str(tmp_path / "events.jsonl"))
    tm = TrainTelemetry(events=events)
    server = TelemetryHTTPServer(
        tm.registry, tm.healthz, port=0,
        trace=TraceCapture(root=str(tmp_path / "profiles"))).start()
    try:
        state = _run_train(tmp_path, tm, num_steps=5)
        metrics_text = urllib.request.urlopen(
            server.url + "/metrics", timeout=10).read().decode()
        health = json.load(urllib.request.urlopen(
            server.url + "/healthz", timeout=10))
        req = urllib.request.Request(
            server.url + "/debug/trace",
            data=json.dumps({"duration_ms": 150}).encode(), method="POST")
        trace_reply = json.load(urllib.request.urlopen(req, timeout=10))
        server.trace.stop()
    finally:
        server.shutdown()
        events.close()
    return dict(state=state, metrics=metrics_text, health=health,
                trace=trace_reply, events_path=events.path, telemetry=tm)


def test_train_run_is_live_scrapable(scraped_run):
    text = scraped_run["metrics"]
    assert int(scraped_run["state"].step) == 5
    assert "train_steps_total 5" in text
    assert "train_recompiles_total 0" in text
    # wall-time split histograms populated once per step
    assert "train_step_seconds_count 5" in text
    assert "train_data_wait_seconds_count 5" in text
    assert "train_metric_drain_seconds_count" in text
    assert "train_checkpoint_seconds_count 2" in text  # boundary + final
    # memory gauges refreshed at the drain
    assert "train_host_rss_bytes" in text


def test_healthz_reports_last_step_age(scraped_run):
    health = scraped_run["health"]
    assert health["status"] == "complete"
    assert health["step"] == 5 and health["total_steps"] == 5
    assert health["last_step_age_s"] is not None
    assert 0 <= health["last_step_age_s"] < 600
    assert health["recompiles"] == 0


def test_debug_trace_endpoint_opens_window(scraped_run):
    reply = scraped_run["trace"]
    assert reply["duration_ms"] == 150
    assert "trace_dir" in reply


def test_gru_convergence_histogram_populated(scraped_run):
    # gru_telemetry=True with train_iters=2 -> one delta per step
    hist = scraped_run["telemetry"].gru_delta
    assert hist.count == 5
    assert hist.mean() > 0  # params move, so consecutive preds differ


def test_event_log_replays_into_coherent_timeline(scraped_run):
    recs = list(replay(scraped_run["events_path"]))
    kinds = [r["event"] for r in recs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    start = recs[0]
    assert start["schema_version"] == telemetry.SCHEMA_VERSION
    assert start["model_config"]["n_gru_layers"] == 1  # config snapshot
    assert start["train_config"]["num_steps"] == 5
    assert start["run"]["platform"] == "cpu"  # device topology
    assert "step_stats" in kinds and "checkpoint" in kinds
    stats = [r for r in recs if r["event"] == "step_stats"]
    assert all(a["step"] <= b["step"] for a, b in zip(stats, stats[1:]))
    assert "loss" in stats[-1]["means"]
    end = recs[-1]
    assert end["status"] == "complete" and end["step"] == 5
    assert [r["seq"] for r in recs] == list(range(len(recs)))
    assert all(a["ts"] <= b["ts"] for a, b in zip(recs, recs[1:]))


def test_telemetry_disabled_adds_no_device_fetches(tmp_path, monkeypatch):
    """The acceptance guarantee: with telemetry off (default) the loop
    issues EXACTLY the fetches the instrumented loop issues — i.e. the
    instrumentation adds none, and disabling it takes the pre-telemetry
    path.  Counted at jax.device_get, the loop's only fetch primitive."""
    real_device_get = jax.device_get
    counts = []

    def run_counting(telemetry_obj, sub):
        calls = [0]

        def counting_get(x):
            calls[0] += 1
            return real_device_get(x)

        monkeypatch.setattr(jax, "device_get", counting_get)
        try:
            _run_train(tmp_path / sub, telemetry_obj, num_steps=2,
                       train_iters=1, gru_telemetry=False)
        finally:
            monkeypatch.setattr(jax, "device_get", real_device_get)
        counts.append(calls[0])

    run_counting(None, "off")
    run_counting(TrainTelemetry(), "on")
    assert counts[0] == counts[1], counts


# ---------------------------------------------------------- telemetry http
def test_telemetry_endpoint_errors():
    from raft_stereo_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("x_total", "t").inc(3)
    server = TelemetryHTTPServer(reg, lambda: {"status": "ok"},
                                 port=0).start()
    try:
        body = urllib.request.urlopen(server.url + "/metrics",
                                      timeout=10).read().decode()
        assert "x_total 3" in body
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(server.url + "/nope", timeout=10)
        assert e.value.code == 404
        bad = urllib.request.Request(server.url + "/debug/trace",
                                     data=b'{"duration_ms": "soon"}',
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(bad, timeout=10)
        assert e.value.code == 400
    finally:
        server.shutdown()


# ------------------------------------------------------------- logger fix
def test_logger_running_mean_uses_actual_count(caplog):
    """Regression (ISSUE 3 satellite): the first flush window holds only
    SUM_FREQ-1 pushes, and the close() drain fewer still — the mean must
    divide by the actual accumulated count, not SUM_FREQ."""
    import logging

    from raft_stereo_tpu.training.logger import SUM_FREQ, Logger

    with caplog.at_level(logging.INFO,
                         logger="raft_stereo_tpu.training.logger"):
        logger = Logger(enable_tensorboard=False)
        for _ in range(SUM_FREQ - 1):  # exactly one flush, 99 pushes
            logger.push({"loss": 2.0})
        assert logger.running_count == 0, "first window must have flushed"
        assert "loss 2.0000" in caplog.text  # old code logged 1.9800
        caplog.clear()
        for _ in range(5):
            logger.push({"loss": 4.0})
        logger.close()  # partial drain: 5 pushes, mean still exact
        assert "loss 4.0000" in caplog.text


def test_logger_context_manager_closes_writer(tmp_path):
    from raft_stereo_tpu.training.logger import Logger

    class _Writer:
        closed = False

        def add_scalar(self, *a, **k):
            pass

        def close(self):
            self.closed = True

    writer = _Writer()
    with Logger(enable_tensorboard=False) as logger:
        logger.writer = writer
        logger.push({"loss": 1.0})
    assert writer.closed
    assert logger.writer is None
