"""Profiling subsystem (raft_stereo_tpu/profiling.py) on the CPU backend."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu import profiling


def test_fps_protocol_warmup_discard():
    proto = profiling.FpsProtocol(warmup=2)
    calls = []

    def fn(x):
        calls.append(x)
        return jnp.asarray(x)

    res = proto.measure(fn, [(i,) for i in range(7)])
    assert len(calls) == 7
    assert res.n_timed == 5  # first 2 discarded
    assert res.fps == pytest.approx(1.0 / res.mean_s)
    assert "fps" in str(res)


def test_fps_protocol_needs_more_than_warmup():
    proto = profiling.FpsProtocol(warmup=50)
    with pytest.raises(ValueError, match="warmup"):
        proto.measure(lambda x: x, [(0,), (1,)])


def test_chained_seconds_per_call_cancels_overhead():
    per_call = 2e-3
    overhead = 20e-3

    def make_chain(k):
        def run():
            time.sleep(overhead + k * per_call)
        return run

    est = profiling.chained_seconds_per_call(make_chain, k_lo=2, k_hi=10,
                                             repeats=2)
    assert est == pytest.approx(per_call, rel=0.5)


def test_trace_writes_profile(tmp_path):
    d = str(tmp_path / "prof")
    with profiling.trace(d):
        with profiling.annotate("matmul-span"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert found, "profiler trace produced no files"


def test_device_memory_stats_dict():
    stats = profiling.device_memory_stats()
    assert isinstance(stats, dict)  # CPU backend may legitimately report {}


class _FakeDevice:
    """Stands in for a jax.Device with a controllable memory_stats."""

    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_device_memory_stats_backend_fallbacks():
    """Backends without memory stats (CPU) report None — the helper must
    degrade to {} and never raise; backends with stats pass them through."""
    assert profiling.device_memory_stats(_FakeDevice(None)) == {}
    assert profiling.device_memory_stats(
        _FakeDevice({"bytes_in_use": 7})) == {"bytes_in_use": 7}
    # a device object without the method at all (exotic backend plugin)
    assert profiling.device_memory_stats(object()) == {}


def test_device_hbm_bytes_cpu_fallback(monkeypatch):
    """device_hbm_bytes feeds the memory-derived full-res gates; on a
    backend with no bytes_limit it must return the caller's fallback, and
    with one it must return the reported capacity."""
    monkeypatch.setattr(profiling, "device_memory_stats", lambda: {})
    assert profiling.device_hbm_bytes(fallback=123) == 123
    monkeypatch.setattr(profiling, "device_memory_stats",
                        lambda: {"bytes_limit": 0})
    assert profiling.device_hbm_bytes(fallback=456) == 456
    monkeypatch.setattr(profiling, "device_memory_stats",
                        lambda: {"bytes_limit": 32 * 2 ** 30})
    assert profiling.device_hbm_bytes(fallback=456) == 32 * 2 ** 30


def test_annotate_names_traced_ops():
    """annotate() is also an XLA op-name scope: ops staged inside the block
    carry the phase name, so device traces break out the model's phases
    (fnet/cnet/corr_pyramid/gru_iter/upsample)."""
    def f(x):
        with profiling.annotate("myphase"):
            return x * 2.0

    ir = jax.jit(f).lower(jnp.ones((4,))).compiler_ir("stablehlo")
    # scope names live in the MLIR location info, which XLA turns into the
    # op metadata that device traces display
    assert "myphase" in ir.operation.get_asm(enable_debug_info=True)


def test_annotate_nesting_composes_scopes():
    """Nested annotate() blocks compose their named scopes in the traced
    graph — ops staged in the inner block carry "outer/inner", so device
    traces keep the phase hierarchy (e.g. gru_iter wrapping the fused-GRU
    kernel's own span)."""
    def f(x):
        with profiling.annotate("outer"):
            y = x + 1.0
            with profiling.annotate("inner"):
                y = y * 2.0
        return y

    ir = jax.jit(f).lower(jnp.ones((4,))).compiler_ir("stablehlo")
    asm = ir.operation.get_asm(enable_debug_info=True)
    assert "outer/inner" in asm  # composed scope on the inner op
    # host-side nesting works too (TraceAnnotation enters/exits cleanly)
    with profiling.annotate("outer"):
        with profiling.annotate("inner"):
            pass


def test_bench_phase_split_math():
    """bench.py's realtime_phase_split line: differencing the 7-iter and
    1-iter forwards attributes per-GRU-iter vs fixed (encoder+) time."""
    import bench

    # synthetic: 0.9 ms fixed + 1.1 ms/iter
    split = bench.phase_split(t_iters_s=0.9e-3 + 7 * 1.1e-3,
                              t_one_iter_s=0.9e-3 + 1.1e-3, iters=7)
    assert split["metric"] == "realtime_phase_split"
    assert split["per_gru_iter_ms"] == pytest.approx(1.1, abs=1e-3)
    assert split["encoder_and_fixed_ms"] == pytest.approx(0.9, abs=1e-3)
    assert split["gru_share_at_7_iters"] == pytest.approx(
        7 * 1.1 / (0.9 + 7 * 1.1), abs=1e-3)


def test_bench_regression_warnings():
    """The warn-on-regression comparison against BASELINE.json's published
    phase split: quiet within the noise band, loud past it."""
    import bench

    good = bench.phase_split(t_iters_s=0.9e-3 + 7 * 0.5e-3,
                             t_one_iter_s=0.9e-3 + 0.5e-3, iters=7)
    assert bench.check_regression(good, fps=150.0) == []

    bad = bench.phase_split(t_iters_s=0.9e-3 + 7 * 5.0e-3,
                            t_one_iter_s=0.9e-3 + 5.0e-3, iters=7)
    warns = bench.check_regression(bad, fps=20.0)
    kinds = " ".join(w["warning"] for w in warns)
    assert "per_gru_iter_ms" in kinds
    assert "north-star" in kinds
