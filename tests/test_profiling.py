"""Profiling subsystem (raft_stereo_tpu/profiling.py) on the CPU backend."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu import profiling


def test_fps_protocol_warmup_discard():
    proto = profiling.FpsProtocol(warmup=2)
    calls = []

    def fn(x):
        calls.append(x)
        return jnp.asarray(x)

    res = proto.measure(fn, [(i,) for i in range(7)])
    assert len(calls) == 7
    assert res.n_timed == 5  # first 2 discarded
    assert res.fps == pytest.approx(1.0 / res.mean_s)
    assert "fps" in str(res)


def test_fps_protocol_needs_more_than_warmup():
    proto = profiling.FpsProtocol(warmup=50)
    with pytest.raises(ValueError, match="warmup"):
        proto.measure(lambda x: x, [(0,), (1,)])


def test_chained_seconds_per_call_cancels_overhead():
    per_call = 2e-3
    overhead = 20e-3

    def make_chain(k):
        def run():
            time.sleep(overhead + k * per_call)
        return run

    est = profiling.chained_seconds_per_call(make_chain, k_lo=2, k_hi=10,
                                             repeats=2)
    assert est == pytest.approx(per_call, rel=0.5)


def test_trace_writes_profile(tmp_path):
    d = str(tmp_path / "prof")
    with profiling.trace(d):
        with profiling.annotate("matmul-span"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert found, "profiler trace produced no files"


def test_device_memory_stats_dict():
    stats = profiling.device_memory_stats()
    assert isinstance(stats, dict)  # CPU backend may legitimately report {}
