"""Multi-host runtime pieces testable in one process: per-process loader
sharding determinism and the distributed bootstrap's single-process path."""

import numpy as np
import pytest

from raft_stereo_tpu.data.loader import StereoLoader
from raft_stereo_tpu.parallel import distributed


class _ArrayDataset:
    """Minimal StereoDataset stand-in: index -> unique recognizable sample."""

    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i, epoch=0):
        return {"x": np.full((2, 2), i, np.float32)}


def _collect(loader, n):
    it = iter(loader)
    return [next(it) for _ in range(n)]


def test_process_shards_partition_each_global_batch():
    ds = _ArrayDataset(16)
    full = StereoLoader(ds, batch_size=8, num_workers=0, epochs=1, seed=7)
    shards = [StereoLoader(ds, batch_size=8, num_workers=0, epochs=1, seed=7,
                           process_index=p, process_count=2)
              for p in range(2)]
    full_batches = _collect(full, 2)
    shard_batches = [_collect(s, 2) for s in shards]
    for b in range(2):
        assert shard_batches[0][b]["x"].shape == (4, 2, 2)
        recombined = np.concatenate(
            [shard_batches[0][b]["x"], shard_batches[1][b]["x"]])
        np.testing.assert_array_equal(recombined, full_batches[b]["x"])


def test_process_shard_validation():
    ds = _ArrayDataset(8)
    with pytest.raises(ValueError, match="divisible"):
        StereoLoader(ds, batch_size=6, process_count=4)
    with pytest.raises(ValueError, match="out of range"):
        StereoLoader(ds, batch_size=4, process_index=2, process_count=2)


def test_initialize_single_process_noop():
    distributed.initialize()  # must not raise or hang in 1-process runs
    kw = distributed.loader_shard_kwargs()
    assert kw == {"process_index": 0, "process_count": 1}


def test_any_process_single_process_identity():
    assert distributed.any_process(True) is True
    assert distributed.any_process(False) is False
