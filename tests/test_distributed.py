"""Multi-host runtime pieces testable in one process: per-process loader
sharding determinism and the distributed bootstrap's single-process path."""

import numpy as np
import pytest

from raft_stereo_tpu.data.loader import StereoLoader
from raft_stereo_tpu.parallel import distributed


class _ArrayDataset:
    """Minimal StereoDataset stand-in: index -> unique recognizable sample."""

    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i, epoch=0):
        return {"x": np.full((2, 2), i, np.float32)}


def _collect(loader, n):
    it = iter(loader)
    return [next(it) for _ in range(n)]


def test_process_shards_partition_each_global_batch():
    ds = _ArrayDataset(16)
    full = StereoLoader(ds, batch_size=8, num_workers=0, epochs=1, seed=7)
    shards = [StereoLoader(ds, batch_size=8, num_workers=0, epochs=1, seed=7,
                           process_index=p, process_count=2)
              for p in range(2)]
    full_batches = _collect(full, 2)
    shard_batches = [_collect(s, 2) for s in shards]
    for b in range(2):
        assert shard_batches[0][b]["x"].shape == (4, 2, 2)
        recombined = np.concatenate(
            [shard_batches[0][b]["x"], shard_batches[1][b]["x"]])
        np.testing.assert_array_equal(recombined, full_batches[b]["x"])


def test_process_shard_validation():
    ds = _ArrayDataset(8)
    with pytest.raises(ValueError, match="divisible"):
        StereoLoader(ds, batch_size=6, process_count=4)
    with pytest.raises(ValueError, match="out of range"):
        StereoLoader(ds, batch_size=4, process_index=2, process_count=2)


def test_initialize_single_process_noop():
    distributed.initialize()  # must not raise or hang in 1-process runs
    kw = distributed.loader_shard_kwargs()
    assert kw == {"process_index": 0, "process_count": 1}


def test_any_process_single_process_identity():
    assert distributed.any_process(True) is True
    assert distributed.any_process(False) is False


@pytest.mark.slow
def test_two_process_training_matches_single(tmp_path):
    """REAL 2-process distributed run: jax.distributed over a localhost
    coordinator, batch assembled with make_array_from_process_local_data,
    two SPMD steps.  Both processes must agree bit-exactly with each other,
    and match a single-process run of the same global batches."""
    outs = _spawn_workers(tmp_path, "data")
    r0, r1 = np.load(outs[0]), np.load(outs[1])
    # replicated state must be IDENTICAL across processes
    np.testing.assert_array_equal(r0["params"], r1["params"])
    np.testing.assert_array_equal(r0["losses"], r1["losses"])

    # single-process reference on the same global batches / mesh shape
    import jax
    import jax.numpy as jnp  # noqa: F401

    from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
    from raft_stereo_tpu.parallel.mesh import make_mesh, replicate, shard_batch
    from raft_stereo_tpu.training.state import create_train_state
    from raft_stereo_tpu.training.step import make_train_step

    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), corr_levels=2,
                            fnet_dim=32)
    tcfg = TrainConfig(batch_size=8, train_iters=2, num_steps=10,
                       image_size=(32, 48))
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               image_shape=(1, 32, 48, 3))
    mesh = make_mesh(n_data=4, devices=jax.devices()[:4])
    state = replicate(state, mesh)
    step_fn = make_train_step(tcfg, mesh=mesh, donate=False)
    losses = []
    for step in range(2):
        rng = np.random.default_rng(100 + step)
        g = {"image1": rng.uniform(0, 255, (8, 32, 48, 3)).astype(np.float32),
             "image2": rng.uniform(0, 255, (8, 32, 48, 3)).astype(np.float32),
             "flow": rng.normal(0, 5, (8, 32, 48)).astype(np.float32),
             "valid": np.ones((8, 32, 48), np.float32)}
        state, metrics = step_fn(state, shard_batch(g, mesh))
        losses.append(float(metrics["loss"]))
    flat = np.concatenate([np.ravel(np.asarray(jax.device_get(x)))
                           for x in jax.tree_util.tree_leaves(state.params)])
    np.testing.assert_allclose(r0["losses"], np.asarray(losses), rtol=1e-6)
    # The cross-process gradient psum reassociates differently from the
    # in-process one, and AdamW normalizes gradients (m/sqrt(v)), so an
    # eps-scale gradient difference moves params by O(lr) per step: observed
    # max |diff| ~3e-4 over 2 steps at lr=2e-4.  Losses above agree to 1e-6;
    # bit-exactness is asserted ACROSS PROCESSES (the SPMD guarantee), not
    # across collective implementations.
    np.testing.assert_allclose(r0["params"], flat, rtol=0, atol=5e-4)


def _spawn_workers(tmp_path, mode):
    import os
    import socket
    import subprocess
    import sys

    sock = socket.socket()
    sock.bind(("localhost", 0))
    port = sock.getsockname()[1]
    sock.close()
    coord = f"localhost:{port}"

    worker = os.path.join(os.path.dirname(__file__), "distributed_worker.py")
    outs = [str(tmp_path / f"proc{i}.npz") for i in range(2)]
    env = {**os.environ, "PYTHONPATH": os.pathsep.join(
        [os.path.dirname(os.path.dirname(worker))]
        + os.environ.get("PYTHONPATH", "").split(os.pathsep))}
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), "2", coord, outs[i], mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    # Drain BOTH workers before asserting: a first-worker failure must not
    # leak the second as an orphan blocked on the dead coordinator, and
    # both logs should be available for diagnosis.
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(out.decode(errors="replace"))
    for p, log_text in zip(procs, logs):
        assert p.returncode == 0, log_text[-3000:]
    return outs


@pytest.mark.slow
def test_two_process_rows_gru_training_matches_single(tmp_path):
    """REAL 2-process run with the ROWS axis laid ACROSS the processes: the
    full-loop context-parallel executor's per-iteration halo ppermute rides
    the cross-process link (the multi-host analog of sequence parallelism
    over DCN).  Both processes agree bit-exactly; the run matches a
    single-process (data=2, rows=2) mesh on the same global batches."""
    outs = _spawn_workers(tmp_path, "rows")
    r0, r1 = np.load(outs[0]), np.load(outs[1])
    np.testing.assert_array_equal(r0["params"], r1["params"])
    np.testing.assert_array_equal(r0["losses"], r1["losses"])

    import jax

    from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
    from raft_stereo_tpu.parallel.mesh import ROWS_AXIS, make_mesh, \
        replicate, shard_batch
    from raft_stereo_tpu.parallel.rows_sharded import rows_sharding
    from raft_stereo_tpu.training.state import create_train_state
    from raft_stereo_tpu.training.step import make_train_step

    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), corr_levels=2,
                            fnet_dim=32, rows_shards=2, rows_gru=True,
                            rows_gru_halo=12)
    h, w, batch = 192, 64, 2
    tcfg = TrainConfig(batch_size=batch, train_iters=2, num_steps=10,
                       image_size=(h, w), data_parallel=2)
    mesh = make_mesh(n_data=2, n_corr=1, n_rows=2, devices=jax.devices()[:4])
    with rows_sharding(mesh, axis=ROWS_AXIS):
        state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                                   image_shape=(1, h, w, 3))
    state = replicate(state, mesh)
    step_fn = make_train_step(tcfg, mesh=mesh, donate=False)
    losses = []
    for step in range(2):
        rng = np.random.default_rng(100 + step)
        g = {"image1": rng.uniform(0, 255, (batch, h, w, 3)).astype(np.float32),
             "image2": rng.uniform(0, 255, (batch, h, w, 3)).astype(np.float32),
             "flow": rng.normal(0, 5, (batch, h, w)).astype(np.float32),
             "valid": np.ones((batch, h, w), np.float32)}
        with rows_sharding(mesh, axis=ROWS_AXIS):
            state, metrics = step_fn(state, shard_batch(g, mesh))
        losses.append(float(metrics["loss"]))
    flat = np.concatenate([np.ravel(np.asarray(jax.device_get(x)))
                           for x in jax.tree_util.tree_leaves(state.params)])
    np.testing.assert_allclose(r0["losses"], np.asarray(losses), rtol=1e-6)
    np.testing.assert_allclose(r0["params"], flat, rtol=0, atol=5e-4)
