"""Context-parallel GRU loop (parallel/rows_gru.py) vs the plain model.

The executor's claim is exactness up to float reassociation on OWNED rows
when the halo covers the update block's per-iteration row receptive field —
these tests are the empirical check of that receptive-field audit
(``default_gru_halo``), in both test and train modes, including parameter
gradients (the whole point: full-resolution TRAINING across chips)."""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
from raft_stereo_tpu.models.raft_stereo import RAFTStereo
from raft_stereo_tpu.parallel.rows_sharded import rows_sharding


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _small_cfg(**kw):
    """3 GRU levels (exercises both cross-resolution interp sites), small
    dims, XLA 'reg' corr — the pure-XLA correctness reference backend."""
    base = dict(n_gru_layers=3, hidden_dims=(48, 48, 48), fnet_dim=96,
                corr_levels=2, corr_radius=3, corr_backend="reg")
    base.update(kw)
    return RaftStereoConfig(**base)


def _pair(rng, h, w, b=1):
    img1 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
    return img1, img2


@pytest.mark.slow
def test_rows_gru_test_mode_matches_plain(rng):
    cfg = _small_cfg()
    cfg_r = dataclasses.replace(cfg, rows_shards=2, rows_gru=True,
                                rows_gru_halo=12)
    img1, img2 = _pair(rng, 192, 48)   # fine level 48 rows: slab 24 = 2*halo
    model = RAFTStereo(cfg)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                   test_mode=True)
    low_ref, up_ref = model.apply(v, img1, img2, iters=3, test_mode=True)

    with rows_sharding(_mesh(2)):
        low_r, up_r = jax.jit(
            lambda v, a, b: RAFTStereo(cfg_r).apply(v, a, b, iters=3,
                                                    test_mode=True)
        )(v, img1, img2)
    assert low_r.shape == low_ref.shape and up_r.shape == up_ref.shape
    np.testing.assert_allclose(np.asarray(low_r), np.asarray(low_ref),
                               rtol=1e-3, atol=5e-3)
    np.testing.assert_allclose(np.asarray(up_r), np.asarray(up_ref),
                               rtol=1e-3, atol=5e-3)


@pytest.mark.slow
def test_rows_gru_train_mode_matches_plain(rng):
    """Per-iteration full-resolution predictions equal the plain scan's —
    including through the remat(save_only corr_lookup) policy, which the
    sharded executor applies identically."""
    cfg = _small_cfg()
    cfg_r = dataclasses.replace(cfg, rows_shards=2, rows_gru=True,
                                rows_gru_halo=12)
    img1, img2 = _pair(rng, 192, 48)
    model = RAFTStereo(cfg)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                   test_mode=True)
    ups_ref = model.apply(v, img1, img2, iters=3)

    with rows_sharding(_mesh(2)):
        ups_r = jax.jit(
            lambda v, a, b: RAFTStereo(cfg_r).apply(v, a, b, iters=3)
        )(v, img1, img2)
    assert ups_r.shape == ups_ref.shape
    np.testing.assert_allclose(np.asarray(ups_r), np.asarray(ups_ref),
                               rtol=1e-3, atol=5e-3)


def test_rows_gru_config_validation():
    with pytest.raises(ValueError, match="rows_shards > 1"):
        RaftStereoConfig(rows_gru=True)
    with pytest.raises(ValueError, match="unsupported"):
        RaftStereoConfig(rows_gru=True, rows_shards=2, corr_w2_shards=2)
    with pytest.raises(ValueError, match="multiple of"):
        RaftStereoConfig(rows_gru=True, rows_shards=2, rows_gru_halo=10)


@pytest.mark.slow
def test_rows_gru_geometry_validation(rng):
    """A slab shorter than 2*halo cannot be sourced by one ppermute — the
    trace fails with the fix-it message instead of silently losing rows."""
    cfg_r = _small_cfg(rows_shards=2, rows_gru=True, rows_gru_halo=16)
    img1, img2 = _pair(rng, 96, 48)    # fine 24 rows -> slab 12 < 32
    model = RAFTStereo(cfg_r)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                   test_mode=True)
    with rows_sharding(_mesh(2)):
        with pytest.raises(ValueError, match="ppermute"):
            model.apply(v, img1, img2, iters=1, test_mode=True)


@pytest.mark.slow
def test_rows_gru_training_gradients_match(rng):
    """Loss AND parameter gradients through the sharded loop equal the
    single-device ones on a (data=2, rows=2) mesh — halo-exchange ppermutes
    transpose exactly and cropped pollution rows carry zero cotangent.

    Assertion scheme mirrors the trunk-sharding gradient test
    (tests/test_rows_sharded.py): per-leaf deviations relative to the
    leaf's own gradient scale, bulk-tight with bounded isolated outliers —
    this untrained instance-norm net's gradients reassociate at the
    percent level even between jit and no-jit runs of the SAME model, while
    the bug class this guards (a mis-reduced collective, a lost halo row's
    cotangent) shifts most entries by integer factors."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_stereo_tpu.parallel.mesh import ROWS_AXIS, make_mesh
    from raft_stereo_tpu.parallel.rows_sharded import rows_sharding as rs
    from raft_stereo_tpu.training.loss import sequence_loss

    cfg = _small_cfg()
    cfg_r = dataclasses.replace(cfg, rows_shards=2, rows_gru=True,
                                rows_gru_halo=12)
    img1, img2 = _pair(rng, 192, 48, b=2)
    flow_gt = jnp.asarray(rng.uniform(-8, 0, (2, 192, 48)), jnp.float32)
    valid = jnp.ones((2, 192, 48), jnp.float32)
    model = RAFTStereo(cfg)
    variables = model.init(jax.random.PRNGKey(0), img1, img2, iters=1)
    batch_stats = variables.get("batch_stats", {})

    def loss_of(m):
        def f(params):
            ups = m.apply({"params": params, "batch_stats": batch_stats},
                          img1, img2, iters=2)
            loss, _ = sequence_loss(ups, flow_gt, valid)
            return loss
        return f

    loss_ref, g_ref = jax.value_and_grad(loss_of(model))(
        variables["params"])

    mesh = make_mesh(n_data=2, n_corr=1, n_rows=2,
                     devices=jax.devices()[:4])
    repl = NamedSharding(mesh, P())
    with rs(mesh, axis=ROWS_AXIS):
        loss_r, g_r = jax.jit(
            jax.value_and_grad(loss_of(RAFTStereo(cfg_r))),
            in_shardings=(repl,), out_shardings=(repl, repl),
        )(variables["params"])

    np.testing.assert_allclose(float(loss_r), float(loss_ref), rtol=1e-4)
    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_r = dict(jax.tree_util.tree_leaves_with_path(g_r))
    global_scale = max(float(np.max(np.abs(np.asarray(g))))
                       for _, g in flat_ref)
    skipped = 0
    for path, leaf in flat_ref:
        g_r_leaf = np.asarray(flat_r[path])
        g_ref_leaf = np.asarray(leaf)
        scale = float(np.max(np.abs(g_ref_leaf)))
        if scale < 1e-3 * global_scale:
            skipped += 1  # shift-invariant-norm biases: zero true gradient
            continue
        rel = np.abs(g_r_leaf - g_ref_leaf) / scale
        keystr = jax.tree_util.keystr(path)
        # q99 bound is 5e-3 (vs the trunk test's 3e-3): this config is
        # 3-level/192-row and the trunk executor's own reassociation
        # measures q99 0.0032 here; the guarded bug class (XLA SPMD conv
        # kernel-grad double-count under (batch x rows) sharding) measures
        # q99 ~0.3 — two orders above the bound.
        assert float(np.quantile(rel, 0.99)) < 5e-3, \
            f"bulk grad mismatch at {keystr}: q99 {np.quantile(rel, 0.99)}"
        assert float(rel.max()) < 3e-2, \
            f"grad outlier at {keystr}: max rel-to-scale {rel.max()}"
    assert skipped < len(flat_ref) // 2, \
        f"too many near-zero-grad leaves skipped ({skipped})"


@pytest.mark.slow
def test_rows_gru_slow_fast_two_level(rng):
    """The realtime-style coupling (2 GRU levels + slow_fast extra mid
    updates) stays exact: the mid level's tripled per-iteration shrink is
    covered by halo/2."""
    cfg = _small_cfg(n_gru_layers=2, hidden_dims=(48, 48),
                     slow_fast_gru=True)
    cfg_r = dataclasses.replace(cfg, rows_shards=2, rows_gru=True,
                                rows_gru_halo=16)
    img1, img2 = _pair(rng, 256, 48)   # fine 64 rows: slab 32 = 2*halo
    model = RAFTStereo(cfg)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                   test_mode=True)
    _, up_ref = model.apply(v, img1, img2, iters=3, test_mode=True)
    with rows_sharding(_mesh(2)):
        _, up_r = jax.jit(
            lambda v, a, b: RAFTStereo(cfg_r).apply(v, a, b, iters=3,
                                                    test_mode=True)
        )(v, img1, img2)
    np.testing.assert_allclose(np.asarray(up_r), np.asarray(up_ref),
                               rtol=1e-3, atol=5e-3)


@pytest.mark.slow
def test_rows_gru_train_loop_auto_wires(tmp_path, rng):
    """train() with rows_gru=True: the loop builds the mesh, holds the
    rows_sharding context around tracing, steps the FULL-loop sharded
    executor end to end (loader, device prefetch, checkpointing), and the
    periodic validator's single-device normalization strips rows_gru."""
    from raft_stereo_tpu.training.train_loop import train

    cfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), fnet_dim=64,
                           corr_levels=2, corr_radius=3, corr_backend="reg",
                           rows_shards=2, rows_gru=True, rows_gru_halo=12)
    tcfg = TrainConfig(batch_size=2, train_iters=2, valid_iters=2,
                       num_steps=2, image_size=(192, 64), data_parallel=1,
                       validation_frequency=2, seed=3)

    class Stream:
        def __iter__(self):
            gen = np.random.default_rng(7)
            while True:
                yield {
                    "image1": gen.integers(0, 256, (2, 192, 64, 3)).astype(
                        np.uint8),
                    "image2": gen.integers(0, 256, (2, 192, 64, 3)).astype(
                        np.uint8),
                    "flow": gen.uniform(-8, 0, (2, 192, 64)).astype(
                        np.float32),
                    "valid": np.ones((2, 192, 64), np.float32)}

    seen = {}

    def validate_fn(variables, model_cfg=None):
        seen["cfg"] = model_cfg
        return {"probe": 1.0}

    state = train(cfg, tcfg, name="rows_gru",
                  checkpoint_dir=str(tmp_path / "ck"),
                  log_dir=str(tmp_path / "runs"), loader=Stream(),
                  validate_fn=validate_fn)
    assert int(state.step) == 2
    assert seen["cfg"].rows_gru  # authoritative cfg reaches the hook
    from raft_stereo_tpu.eval.validate import single_device_cfg
    norm = single_device_cfg(seen["cfg"])
    assert not norm.rows_gru and norm.rows_shards == 1
    leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(state.params)]
    assert all(np.all(np.isfinite(l)) for l in leaves)
