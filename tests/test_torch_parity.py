"""Golden numeric parity vs the reference PyTorch implementation.

Builds the ACTUAL reference model (imported from /root/reference) with random
weights on CPU, imports its state_dict through our torch-checkpoint importer,
and asserts the two frameworks produce the same disparity field.  This
validates the importer AND every op in the forward stack (encoders, norms,
GRUs, correlation, sampling, convex upsampling) in one shot.
"""

import os
import sys
from types import SimpleNamespace

import numpy as np
import pytest

torch = pytest.importorskip("torch")

REFERENCE = "/root/reference"

# Parity needs the reference repo's source tree next to torch itself —
# skip as an absent optional dependency (typed, module-level) so real
# numeric regressions stay distinguishable from an image without the
# reference checkout.
if not os.path.isdir(os.path.join(REFERENCE, "core")):
    pytest.skip(f"reference PyTorch implementation not present at "
                f"{REFERENCE}", allow_module_level=True)


def _load_reference_model(args):
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    from core.raft_stereo import RAFTStereo as TorchRAFTStereo
    return TorchRAFTStereo(args)


def _reference_args(**kw):
    base = dict(hidden_dims=[128, 128, 128], corr_implementation="reg",
                shared_backbone=False, corr_levels=4, corr_radius=4,
                n_downsample=2, context_norm="batch", slow_fast_gru=False,
                n_gru_layers=3, mixed_precision=False)
    base.update(kw)
    return SimpleNamespace(**base)


@pytest.mark.parametrize("ref_kw,iters,hw", [
    ({}, 5, (64, 96)),
    # n_downsample=3 needs W/8 >= 2^corr_levels for the reference's pyramid
    ({"n_gru_layers": 2, "n_downsample": 3, "shared_backbone": True,
      "slow_fast_gru": True}, 3, (96, 160)),
])
def test_forward_parity(tmp_path, rng, ref_kw, iters, hw):
    import jax.numpy as jnp

    from raft_stereo_tpu.io.torch_import import import_torch_checkpoint
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    args = _reference_args(**ref_kw)
    torch.manual_seed(0)
    tmodel = _load_reference_model(args)
    tmodel.eval()

    pth = str(tmp_path / "ref.pth")
    torch.save(tmodel.state_dict(), pth)

    cfg, variables = import_torch_checkpoint(
        pth, slow_fast_gru=args.slow_fast_gru)
    assert cfg.n_gru_layers == args.n_gru_layers
    assert cfg.n_downsample == args.n_downsample
    assert cfg.shared_backbone == args.shared_backbone

    h, w = hw
    img1 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)

    with torch.no_grad():
        t1 = torch.from_numpy(img1.transpose(0, 3, 1, 2))
        t2 = torch.from_numpy(img2.transpose(0, 3, 1, 2))
        _, t_up = tmodel(t1, t2, iters=iters, test_mode=True)
    t_up = t_up.numpy()[:, 0]  # (1, H, W)

    model = RAFTStereo(cfg)
    _, j_up = model.apply(variables, jnp.asarray(img1), jnp.asarray(img2),
                          iters=iters, test_mode=True)
    j_up = np.asarray(j_up)

    diff = np.abs(j_up - t_up)
    assert diff.max() < 5e-3, (
        f"parity broken: max {diff.max():.5f}, mean {diff.mean():.6f}")


def test_importer_rejects_shape_mismatch(tmp_path):
    from raft_stereo_tpu.io.torch_import import import_torch_checkpoint

    args = _reference_args()
    torch.manual_seed(0)
    tmodel = _load_reference_model(args)
    sd = tmodel.state_dict()
    # corrupt one tensor's shape
    sd["update_block.flow_head.conv2.bias"] = torch.zeros(7)
    pth = str(tmp_path / "bad.pth")
    torch.save(sd, pth)
    with pytest.raises(ValueError, match="shape"):
        import_torch_checkpoint(pth)


def test_train_mode_parity(tmp_path, rng):
    """Per-iteration predictions (the sequence-loss inputs) also match."""
    import jax.numpy as jnp

    from raft_stereo_tpu.io.torch_import import import_torch_checkpoint
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    args = _reference_args()
    torch.manual_seed(1)
    tmodel = _load_reference_model(args)
    tmodel.eval()
    pth = str(tmp_path / "ref.pth")
    torch.save(tmodel.state_dict(), pth)
    cfg, variables = import_torch_checkpoint(pth)

    h, w = 64, 96
    img1 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
    iters = 3

    with torch.no_grad():
        preds = tmodel(torch.from_numpy(img1.transpose(0, 3, 1, 2)),
                       torch.from_numpy(img2.transpose(0, 3, 1, 2)),
                       iters=iters)
    t_preds = np.stack([p.numpy()[:, 0] for p in preds])  # (iters,1,H,W)

    model = RAFTStereo(cfg)
    j_preds = np.asarray(model.apply(variables, jnp.asarray(img1),
                                     jnp.asarray(img2), iters=iters))
    diff = np.abs(j_preds - t_preds)
    assert diff.max() < 5e-3, f"train-mode parity broken: {diff.max():.5f}"
