"""End-to-end model tests: shapes, modes, config variants, gradients."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.models import RAFTStereo


def _init_and_run(cfg, B=1, H=64, W=96, iters=3, test_mode=False, seed=0):
    model = RAFTStereo(cfg)
    rngs = jax.random.PRNGKey(seed)
    img1 = jnp.asarray(
        np.random.default_rng(seed).uniform(0, 255, (B, H, W, 3)), jnp.float32)
    img2 = img1 + 1.0
    variables = model.init(rngs, img1, img2, iters=2, test_mode=True)
    out = model.apply(variables, img1, img2, iters=iters, test_mode=test_mode)
    return variables, out


@pytest.mark.slow
def test_train_mode_shapes():
    cfg = RaftStereoConfig()
    _, preds = _init_and_run(cfg, B=2, H=64, W=96, iters=3)
    assert preds.shape == (3, 2, 64, 96)
    assert np.all(np.isfinite(np.asarray(preds)))


@pytest.mark.slow
def test_test_mode_shapes():
    cfg = RaftStereoConfig()
    _, (disp_low, disp_up) = _init_and_run(cfg, iters=3, test_mode=True)
    assert disp_low.shape == (1, 16, 24)   # 1/4 res (n_downsample=2)
    assert disp_up.shape == (1, 64, 96)


@pytest.mark.slow
@pytest.mark.parametrize("n_gru_layers", [1, 2, 3])
def test_gru_layer_variants(n_gru_layers):
    cfg = RaftStereoConfig(n_gru_layers=n_gru_layers)
    _, preds = _init_and_run(cfg, iters=2)
    assert preds.shape == (2, 1, 64, 96)


@pytest.mark.slow
def test_realtime_config():
    """shared_backbone + n_downsample 3 + 2 GRU layers + slow_fast
    (reference: README.md:84)."""
    cfg = RaftStereoConfig(shared_backbone=True, n_downsample=3,
                           n_gru_layers=2, slow_fast_gru=True,
                           mixed_precision=True, corr_backend="reg_fused")
    _, (disp_low, disp_up) = _init_and_run(cfg, iters=2, test_mode=True)
    assert disp_low.shape == (1, 8, 12)
    assert disp_up.shape == (1, 64, 96)
    assert np.all(np.isfinite(np.asarray(disp_up)))


@pytest.mark.slow
def test_alt_backend_matches_reg():
    """Backend interchangeability — the reference's core contract
    (core/raft_stereo.py:90-100)."""
    out = {}
    for backend in ("reg", "alt"):
        cfg = RaftStereoConfig(corr_backend=backend)
        variables, preds = _init_and_run(cfg, iters=2, seed=7)
        out[backend] = np.asarray(preds)
    np.testing.assert_allclose(out["reg"], out["alt"], rtol=1e-4, atol=1e-3)


@pytest.mark.slow
def test_flow_init_warm_start():
    cfg = RaftStereoConfig()
    model = RAFTStereo(cfg)
    img = jnp.zeros((1, 64, 96, 3))
    variables = model.init(jax.random.PRNGKey(0), img, img, iters=1,
                           test_mode=True)
    flow_init = jnp.full((1, 16, 24), -3.0)
    disp_low, _ = model.apply(variables, img, img, iters=1,
                              flow_init=flow_init, test_mode=True)
    # one GRU iteration moves the field but it should stay near the init
    assert np.abs(np.asarray(disp_low).mean() - (-3.0)) < 3.0


@pytest.mark.slow
def test_gradients_flow():
    cfg = RaftStereoConfig(n_gru_layers=2)
    model = RAFTStereo(cfg)
    img1 = jnp.ones((1, 32, 64, 3)) * 100
    img2 = jnp.ones((1, 32, 64, 3)) * 120
    variables = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                           test_mode=True)

    def loss_fn(params):
        preds = model.apply({**variables, "params": params}, img1, img2,
                            iters=2)
        return jnp.mean(jnp.abs(preds))

    grads = jax.grad(loss_fn)(variables["params"])
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in leaves)
    # the fnet and update block must receive gradient signal
    total = sum(float(jnp.sum(jnp.abs(g))) for g in leaves)
    assert total > 0


def test_sequential_fnet_matches_batched():
    """The full-res sequential-fnet path (peak-HBM halving) is numerically
    identical to the batched concat path."""
    import dataclasses

    cfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), corr_levels=2,
                           fnet_dim=32)
    model = RAFTStereo(cfg)
    rng = np.random.default_rng(3)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1, test_mode=True)

    _, up_batched = model.apply(v, img1, img2, iters=2, test_mode=True)
    cfg_seq = dataclasses.replace(cfg, sequential_fnet_pixels=0)
    _, up_seq = RAFTStereo(cfg_seq).apply(v, img1, img2, iters=2,
                                          test_mode=True)
    # batch-2 vs batch-1 convolutions reassociate differently (~1e-6 on the
    # feature maps), and the untrained GRU amplifies ~5x/iteration — same
    # drift scale as the sharded-model comparison (test_parallel).
    np.testing.assert_allclose(np.asarray(up_seq), np.asarray(up_batched),
                               rtol=1e-3, atol=1e-3)


def test_fullres_gates_are_memory_derived(monkeypatch):
    """Path-selection pins (VERDICT round 2 weak #5): the sequential-fnet
    threshold and banded band height derive from device HBM, scale with it,
    and respect their config overrides."""
    from raft_stereo_tpu.models import banded
    from raft_stereo_tpu.models.raft_stereo import sequential_fnet_threshold

    cfg = RaftStereoConfig()
    # CPU backend reports no bytes_limit -> 16 GiB fallback: the derived
    # threshold must keep KITTI/SceneFlow batched and Middlebury-F-class
    # frames sequential (the round-2 proven split).
    thr = sequential_fnet_threshold(cfg)
    assert 544 * 960 < thr <= 1088 * 1984, thr
    # Explicit override wins, including the force-sequential 0.
    import dataclasses
    assert sequential_fnet_threshold(
        dataclasses.replace(cfg, sequential_fnet_pixels=0)) == 0
    assert sequential_fnet_threshold(
        dataclasses.replace(cfg, sequential_fnet_pixels=7)) == 7

    # Threshold scales linearly with HBM capacity.
    import raft_stereo_tpu.profiling as prof
    monkeypatch.setattr(prof, "device_memory_stats",
                        lambda: {"bytes_limit": 32 * 2 ** 30})
    assert abs(sequential_fnet_threshold(cfg) - 2 * thr) <= 2

    # Band height: even, clamped, wider images get shorter bands.
    monkeypatch.setattr(prof, "device_memory_stats", lambda: {})
    b_narrow = banded.default_band_rows(1, 1984)
    b_wide = banded.default_band_rows(1, 4608)
    assert b_narrow % 2 == 0 and b_wide % 2 == 0
    assert banded._BAND_MIN <= b_wide <= b_narrow <= banded._BAND_MAX
    # At the round-2 measurement shape the derivation reproduces the band
    # that carried FULLRES_r02.json within a factor of ~2.
    assert 128 <= banded.default_band_rows(1, 2880) <= 512
