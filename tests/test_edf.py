"""EDF cross-session frame scheduler tests (round 19, tier-1, no JAX).

The deadline-aware pop policy (serving/batcher.py ``edf=True``) is pure
queue logic, so every contract here runs in milliseconds:

* EDF ordering — deadline-carrying requests pop earliest-deadline-first
  (not FIFO), while expired ones still triage-drop exactly as before;
* bounded slack — a coalescing wait never extends past the nearest
  deadline minus the bucket's measured dispatch latency, and never more
  than ``edf_max_slack_s`` past the head frame's arrival;
* deliberate coalescing — concurrent sessions' frames merge into the
  largest fillable batch instead of an idle worker instantly
  dispatching batch-1;
* no starvation — deadline-less requests sort by their (past) enqueue
  stamp, so a flood of future-deadline stream frames can never starve
  plain traffic;
* policy-off pin — ``edf=False`` (the default) is the exact r11
  continuous-batching pop: same results, no latency_fn consultation,
  no waiting.
"""

import threading
import time
from concurrent.futures import Future

import pytest

from raft_stereo_tpu.serving.batcher import (BucketQueue, DeadlineExceeded,
                                             Request, edf_key,
                                             edf_slack_end)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _req(bucket=(64, 64), t_enqueue=0.0, deadline=None, tier=None,
         family=None):
    return Request(bucket=bucket, payload=None, future=Future(),
                   t_enqueue=t_enqueue, deadline=deadline, tier=tier,
                   family=family)


# ------------------------------------------------------------- pure helpers
def test_edf_key_orders_deadlines_and_enqueue_stamps():
    a = _req(t_enqueue=10.0, deadline=20.0)
    b = _req(t_enqueue=11.0, deadline=15.0)
    plain = _req(t_enqueue=12.0)          # deadline-less
    assert edf_key(b) < edf_key(a)
    # a deadline-less request's key is its (past) enqueue stamp — it
    # sorts ahead of every live (future) deadline
    assert edf_key(plain) < edf_key(b)


def test_edf_slack_end_never_exceeds_nearest_deadline_minus_latency():
    now = 100.0
    reqs = [_req(t_enqueue=99.0, deadline=100.5),
            _req(t_enqueue=99.5, deadline=100.2)]
    # generous max slack: the deadline bound must win
    end = edf_slack_end(reqs, now, max_slack_s=10.0, est_latency_s=0.1)
    assert end == pytest.approx(100.2 - 0.1)
    assert end <= min(r.deadline for r in reqs)
    # the measured dispatch latency is always reserved
    for est in (0.0, 0.05, 0.19):
        end = edf_slack_end(reqs, now, 10.0, est)
        assert end <= 100.2 - est


def test_edf_slack_end_caps_at_head_age_plus_max_slack():
    now = 100.0
    reqs = [_req(t_enqueue=99.98, deadline=200.0)]
    # far deadline: the max-slack anchor (head enqueue + slack) wins,
    # and it is ABSOLUTE — re-evaluating at a later "now" converges
    end = edf_slack_end(reqs, now, max_slack_s=0.05, est_latency_s=0.0)
    assert end == pytest.approx(99.98 + 0.05)
    assert edf_slack_end(reqs, 100.02, 0.05, 0.0) == pytest.approx(end)


def test_edf_slack_end_no_deadlines_means_no_wait():
    now = 50.0
    reqs = [_req(t_enqueue=49.0), _req(t_enqueue=49.5)]
    assert edf_slack_end(reqs, now, 10.0, 0.0) == now


# ------------------------------------------------------------ EDF ordering
def test_edf_pop_orders_earliest_deadline_first():
    clock = FakeClock()
    q = BucketQueue(max_batch=1, batch_sizes=(1,), clock=clock, edf=True,
                    edf_max_slack_s=0.0)
    # same group, deadlines submitted OUT of order
    late = _req(t_enqueue=clock.t, deadline=clock.t + 9.0)
    soon = _req(t_enqueue=clock.t + 0.001, deadline=clock.t + 1.0)
    mid = _req(t_enqueue=clock.t + 0.002, deadline=clock.t + 5.0)
    for r in (late, soon, mid):
        q.submit(r)
    order = [q.pop(timeout=1.0)[0] for _ in range(3)]
    assert order == [soon, mid, late], "EDF must reorder by deadline"


def test_edf_expired_requests_still_triage_drop():
    clock = FakeClock()
    q = BucketQueue(max_batch=2, batch_sizes=(1, 2), clock=clock,
                    edf=True, edf_max_slack_s=0.0)
    dead = _req(t_enqueue=clock.t - 2.0, deadline=clock.t - 1.0)
    live = _req(t_enqueue=clock.t, deadline=clock.t + 10.0)
    q.submit(dead)
    q.submit(live)
    batch = q.pop(timeout=1.0)
    assert batch == [live]
    with pytest.raises(DeadlineExceeded):
        dead.future.result(timeout=1.0)
    assert q.metrics.deadline_missed.value == 1


def test_edf_no_starvation_of_deadline_less_behind_stream_flood():
    clock = FakeClock()
    q = BucketQueue(max_batch=4, batch_sizes=(1, 2, 4), clock=clock,
                    edf=True, edf_max_slack_s=0.0)
    plain = _req(bucket=(32, 32), t_enqueue=clock.t)
    q.submit(plain)
    # a flood of deadline-carrying frames in ANOTHER group, all with
    # future deadlines
    flood = [_req(bucket=(64, 64), t_enqueue=clock.t + 0.001 * i,
                  deadline=clock.t + 0.5 + 0.001 * i)
             for i in range(8)]
    for r in flood:
        q.submit(r)
    first = q.pop(timeout=1.0)
    assert first == [plain], \
        "the deadline-less request must pop first (its enqueue stamp " \
        "is in the past; the flood's deadlines are in the future)"


# ------------------------------------------------------ bounded-slack wait
def test_edf_pop_waits_slack_and_coalesces_into_larger_batch():
    q = BucketQueue(max_batch=4, batch_sizes=(1, 2, 4), edf=True,
                    edf_max_slack_s=10.0)   # deadline bound governs
    now = time.monotonic()
    q.submit(_req(t_enqueue=now, deadline=now + 0.25))
    got = []
    done = threading.Event()

    def worker():
        got.append(q.pop(timeout=5.0))
        done.set()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    # the pop is slack-waiting on the single queued frame; feed three
    # more from "other sessions" — filling the largest batch size must
    # release it immediately (no need to run out the slack)
    time.sleep(0.05)
    assert not done.is_set(), "pop must hold open during the slack"
    for i in range(3):
        q.submit(_req(t_enqueue=time.monotonic(),
                      deadline=now + 0.25 + 0.01 * i))
    assert done.wait(2.0)
    assert len(got[0]) == 4, \
        f"4 concurrent frames must coalesce into one batch-4 pop, " \
        f"got {len(got[0])}"
    assert q.metrics.edf_slack_waits.value >= 1
    q.close()


def test_edf_slack_expiry_dispatches_partial_batch():
    q = BucketQueue(max_batch=4, batch_sizes=(1, 2, 4), edf=True,
                    edf_max_slack_s=0.05)
    now = time.monotonic()
    q.submit(_req(t_enqueue=now, deadline=now + 10.0))
    t0 = time.monotonic()
    batch = q.pop(timeout=5.0)
    waited = time.monotonic() - t0
    assert len(batch) == 1
    # waited roughly the slack, then dispatched — and NEVER anywhere
    # near the 10 s deadline
    assert 0.02 <= waited <= 1.0, waited
    q.close()


def test_edf_latency_fn_reserves_dispatch_time_before_deadline():
    # measured dispatch latency 80 ms, deadline 100 ms out, max slack
    # huge: the wait must end ~20 ms in (deadline - latency), not at
    # the deadline
    calls = []

    def latency_fn(group_key, batch_size):
        calls.append((group_key, batch_size))
        return 0.08

    q = BucketQueue(max_batch=4, batch_sizes=(1, 2, 4), edf=True,
                    edf_max_slack_s=10.0, latency_fn=latency_fn)
    now = time.monotonic()
    q.submit(_req(t_enqueue=now, deadline=now + 0.1))
    t0 = time.monotonic()
    batch = q.pop(timeout=5.0)
    waited = time.monotonic() - t0
    assert len(batch) == 1 and calls
    assert waited <= 0.09, \
        f"pop must dispatch ~(deadline - measured latency), waited " \
        f"{waited * 1e3:.0f} ms"
    q.close()


# ------------------------------------------------------------ policy-off pin
def test_policy_off_pop_path_pinned():
    """edf=False (the default) is the r11 pop, byte-for-byte behavior:
    FIFO-by-head-age group selection, head-k extraction, zero waiting,
    and the latency hook is never consulted."""

    def poisoned_latency_fn(group_key, batch_size):
        raise AssertionError("policy-off pop must never consult the "
                             "latency hook")

    clock = FakeClock()
    q = BucketQueue(max_batch=2, batch_sizes=(1, 2), clock=clock,
                    latency_fn=poisoned_latency_fn)
    assert q.edf is False
    # deadline-carrying requests in "wrong" deadline order: policy off
    # must return them FIFO, not EDF, and must not wait
    a = _req(t_enqueue=clock.t, deadline=clock.t + 9.0)
    b = _req(t_enqueue=clock.t + 0.001, deadline=clock.t + 1.0)
    q.submit(a)
    q.submit(b)
    t0 = time.monotonic()
    batch = q.pop(timeout=1.0)
    assert time.monotonic() - t0 < 0.5
    assert batch == [a, b], "policy off = head-k FIFO extraction"
    assert q.metrics.edf_slack_waits.value == 0
    q.close()


def test_edf_respects_want_filter_and_sizes():
    """The xl worker-class contract survives the EDF policy: a want
    filter still restricts which groups a pop may take."""
    clock = FakeClock()
    q = BucketQueue(max_batch=4, batch_sizes=(1, 2, 4), clock=clock,
                    edf=True, edf_max_slack_s=0.0)
    xl = _req(bucket=(512, 512), t_enqueue=clock.t,
              deadline=clock.t + 1.0, family="xl")
    solo = _req(bucket=(64, 64), t_enqueue=clock.t + 0.001,
                deadline=clock.t + 0.5)
    q.submit(xl)
    q.submit(solo)
    batch = q.pop(timeout=1.0, want=lambda k: k[2] == "xl", sizes=(1,))
    assert batch == [xl]
    batch = q.pop(timeout=1.0, want=lambda k: k[2] != "xl")
    assert batch == [solo]


def test_edf_config_knob_validation():
    with pytest.raises(ValueError, match="edf_max_slack_s"):
        BucketQueue(edf=True, edf_max_slack_s=-1.0)
