"""Spawn-safe dataset helpers for the process-worker loader tests.

Process loader workers unpickle the dataset by importing its defining
module; classes defined inside a test function (or a pytest module not
on the child's import path) cannot cross the spawn boundary, so the
killing dataset lives here (the tests dir is on sys.path — conftest.py —
and spawn children inherit the parent's sys.path).
"""

import os
import signal

import numpy as np


class KillOnceDataset:
    """8 deterministic samples; the FIRST decode of ``kill_index``
    SIGKILLs the decoding process (the OOM-killed worker) after fsyncing
    a marker file, so the respawned worker's retry decodes normally."""

    def __init__(self, marker: str, kill_index: int = 5):
        self.marker = marker
        self.kill_index = kill_index

    def __len__(self):
        return 8

    def __getitem__(self, i, epoch=0):
        if i == self.kill_index and not os.path.exists(self.marker):
            with open(self.marker, "w") as f:
                f.write("killed\n")
                f.flush()
                os.fsync(f.fileno())
            os.kill(os.getpid(), signal.SIGKILL)
        return {"x": np.full((2, 2), float(i) + 100.0 * epoch)}
