"""Serving subsystem tests (tier-1, CPU).

Batcher policy tests run against an injected dispatch callable — no JAX at
all — so bucket grouping, timed flush, deadline triage, shedding, and drain
are exercised in milliseconds.  Service-level tests run a REAL tiny model:
the headline assertions are (a) a micro-batched response is **bitwise
equal** to the same image run alone through ``InferenceRunner`` (chain
mode's contract), and (b) a burst beyond capacity sheds with the typed
``Overloaded`` while everything admitted still completes.
"""

import io
import json
import threading
import time
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from raft_stereo_tpu.serving.batcher import (DeadlineExceeded, MicroBatcher,
                                             Overloaded, Request)
from raft_stereo_tpu.serving.metrics import MetricsRegistry, ServingMetrics

# Pure-XLA backend: the serving tests assert bitwise properties and must
# not depend on the Pallas kernels' CPU interpret path.
TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")
ITERS = 1


# --------------------------------------------------------------- batcher
class _Collector:
    """Dispatch sink recording batches; optionally blocks until released."""

    def __init__(self, block: bool = False):
        self.batches = []
        self.event = threading.Event()
        self._gate = threading.Event()
        if not block:
            self._gate.set()

    def __call__(self, batch):
        self._gate.wait()
        self.batches.append(batch)
        self.event.set()

    def release(self):
        self._gate.set()


def _req(bucket=(64, 96), deadline_s=None):
    now = time.monotonic()
    return Request(bucket=bucket, payload=None, future=Future(),
                   t_enqueue=now,
                   deadline=None if deadline_s is None else now + deadline_s)


def test_batcher_flushes_full_bucket_immediately():
    sink = _Collector()
    b = MicroBatcher(sink, max_batch=3, max_wait_ms=10_000, max_queue=16)
    try:
        reqs = [_req() for _ in range(3)]
        for r in reqs:
            b.submit(r)
        assert sink.event.wait(timeout=5.0), "full bucket must flush at once"
        assert [len(x) for x in sink.batches] == [3]
        assert sink.batches[0] == reqs  # FIFO order preserved
    finally:
        b.close()


def test_batcher_groups_by_shape_bucket():
    sink = _Collector()
    b = MicroBatcher(sink, max_batch=2, max_wait_ms=10_000, max_queue=16)
    try:
        a1, a2 = _req(bucket=(64, 96)), _req(bucket=(64, 96))
        c1, c2 = _req(bucket=(96, 128)), _req(bucket=(96, 128))
        for r in (a1, c1, a2, c2):  # interleaved submission
            b.submit(r)
        deadline = time.monotonic() + 5.0
        while len(sink.batches) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert sorted(tuple(r.bucket for r in batch)
                      for batch in sink.batches) == [
            ((64, 96), (64, 96)), ((96, 128), (96, 128))]
    finally:
        b.close()


def test_batcher_max_wait_flushes_partial_bucket():
    sink = _Collector()
    b = MicroBatcher(sink, max_batch=8, max_wait_ms=30, max_queue=16)
    try:
        t0 = time.monotonic()
        b.submit(_req())
        b.submit(_req())
        assert sink.event.wait(timeout=5.0)
        elapsed = time.monotonic() - t0
        assert [len(x) for x in sink.batches] == [2]
        assert elapsed >= 0.025, "must not flush before max_wait"
    finally:
        b.close()


def test_batcher_deadline_rejection_at_dispatch():
    sink = _Collector()
    b = MicroBatcher(sink, max_batch=8, max_wait_ms=50, max_queue=16)
    try:
        dead = _req(deadline_s=0.001)   # expires long before the 50 ms flush
        live = _req(deadline_s=30.0)
        b.submit(dead)
        b.submit(live)
        with pytest.raises(DeadlineExceeded):
            dead.future.result(timeout=5.0)
        assert sink.event.wait(timeout=5.0)
        assert [len(x) for x in sink.batches] == [1]  # only the live one
        assert sink.batches[0][0] is live
        assert b.metrics.deadline_missed.value == 1
    finally:
        b.close()


def test_batcher_queue_full_sheds_with_typed_overloaded():
    sink = _Collector(block=True)   # saturated worker pool
    b = MicroBatcher(sink, max_batch=2, max_wait_ms=10_000, max_queue=4)
    try:
        for _ in range(4):
            b.submit(_req())
        # bucket flushes at 2, but dispatch is blocked -> 2 drain at most
        time.sleep(0.05)
        shed = 0
        for _ in range(6):
            try:
                b.submit(_req())
            except Overloaded as e:
                assert not e.draining
                shed += 1
        assert shed > 0, "bounded queue must shed past max_queue"
        assert b.metrics.rejected_queue_full.value == shed
        assert b.depth <= 4
    finally:
        sink.release()
        b.close()


def test_batcher_drain_flushes_then_refuses():
    sink = _Collector()
    b = MicroBatcher(sink, max_batch=8, max_wait_ms=60_000, max_queue=16)
    try:
        reqs = [_req() for _ in range(3)]
        for r in reqs:
            b.submit(r)
        assert not sink.batches, "nothing is due before max_wait"
        assert b.drain(timeout=5.0), "drain must flush the queue"
        assert [len(x) for x in sink.batches] == [3]
        with pytest.raises(Overloaded) as ei:
            b.submit(_req())
        assert ei.value.draining
        assert b.metrics.rejected_draining.value == 1
    finally:
        b.close()


def test_batcher_close_fails_orphans():
    sink = _Collector(block=True)
    b = MicroBatcher(sink, max_batch=1, max_wait_ms=10_000, max_queue=16)
    inflight = _req()
    b.submit(inflight)       # dispatched, stuck in the blocked sink
    time.sleep(0.05)
    orphan = _req()
    b.submit(orphan)
    b.close()
    with pytest.raises(Overloaded):
        orphan.future.result(timeout=5.0)
    sink.release()


# --------------------------------------------------------------- metrics
def test_metrics_exposition_and_percentiles():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "requests")
    g = reg.gauge("depth", "queue depth")
    h = reg.histogram("lat_seconds", "latency", buckets=(0.01, 0.1, 1.0))
    c.inc(3)
    g.set(7)
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    text = reg.render_text()
    assert "# TYPE reqs_total counter" in text
    assert "reqs_total 3" in text
    assert "depth 7" in text
    assert 'lat_seconds_bucket{le="0.01"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 4' in text
    assert "lat_seconds_count 4" in text
    assert h.percentile(50) == pytest.approx(np.percentile(
        [0.005, 0.05, 0.5, 5.0], 50))
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("reqs_total")
    # the standard serving instrument set renders as one scrape
    sm = ServingMetrics(max_batch=4)
    sm.admitted.inc()
    assert "serve_requests_admitted_total 1" in sm.render_text()


# --------------------------------------------------------------- service
@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


def _pairs(n, hw=(48, 64), seed=3):
    rng = np.random.default_rng(seed)
    lefts = [rng.integers(0, 255, hw + (3,), dtype=np.uint8).astype(np.uint8)
             for _ in range(n)]
    rights = [np.roll(l, -3, axis=1) for l in lefts]
    return lefts, rights


def test_service_batched_bitwise_parity_with_solo_runner(tiny_model):
    """The acceptance property: a response that rode a micro-batch is
    bitwise equal to the same pair run alone through InferenceRunner
    (chain mode dispatches through the identical batch-1 program)."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    lefts, rights = _pairs(3)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=3, max_wait_ms=200,
                                   iters=ITERS)) as svc:
        futures = [svc.submit(l, r) for l, r in zip(lefts, rights)]
        results = [f.result(timeout=120) for f in futures]
    assert all(r.batch_size == 3 for r in results), \
        "the three submits must ride one micro-batch"
    for (l, r), res in zip(zip(lefts, rights), results):
        solo_flow, _ = solo(l, r)
        assert res.flow.shape == solo_flow.shape == (48, 64)
        assert np.array_equal(res.flow, solo_flow), \
            "batched response must be bitwise-equal to solo inference"
        assert res.queue_wait_s >= 0 and res.total_s > 0
        np.testing.assert_array_equal(res.disparity, -res.flow)


def test_service_buckets_mixed_shapes_and_unpads_exactly(tiny_model):
    """Different raw shapes that pad to one /32 bucket batch together and
    unpad back to their own sizes; a different bucket compiles separately."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    shapes = [(48, 64), (40, 56), (48, 96)]   # -> (64,64), (64,64), (64,96)
    rng = np.random.default_rng(11)
    pairs = [(rng.integers(0, 255, s + (3,), dtype=np.uint8),) * 2
             for s in shapes]
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=4, max_wait_ms=30,
                                   iters=ITERS)) as svc:
        assert svc.bucket_for((48, 64, 3)) == (64, 64)
        assert svc.bucket_for((40, 56, 3)) == (64, 64)
        assert svc.bucket_for((48, 96, 3)) == (64, 96)
        futures = [svc.submit(l, r) for l, r in pairs]
        results = [f.result(timeout=120) for f in futures]
        for (l, r), res, shape in zip(pairs, results, shapes):
            assert res.flow.shape == shape
            solo_flow, _ = solo(l, r)
            assert np.array_equal(res.flow, solo_flow)
        # metrics saw every stage
        m = svc.metrics
        assert m.completed.value == 3
        assert m.batches.value >= 2          # two distinct buckets
        assert m.queue_wait.count == 3 and m.total_latency.count == 3


def test_service_overload_burst_sheds_and_completes_admitted(tiny_model):
    """Acceptance: a burst of more requests than capacity hits the bounded
    queue — typed Overloaded for the overflow, completion for everything
    admitted, and the accounting adds up."""
    from raft_stereo_tpu.serving import Overloaded, ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, max_wait_ms=1.0, max_queue=4,
                                   iters=ITERS)) as svc:
        svc.infer(lefts[0], rights[0], timeout=120)   # warm the executable
        futures, shed = [], 0
        for _ in range(40):
            try:
                futures.append(svc.submit(lefts[0], rights[0]))
            except Overloaded:
                shed += 1
        assert shed > 0, "burst beyond max_queue must shed"
        results = [f.result(timeout=120) for f in futures]
        assert all(np.isfinite(r.flow).all() for r in results)
        m = svc.metrics
        assert m.admitted.value == 1 + len(futures)
        assert m.rejected_queue_full.value == shed
        assert m.completed.value == 1 + len(futures)
        assert m.batch_occupancy.count == m.batches.value
        # occupancy never exceeds the configured max_batch
        assert m.batch_occupancy.percentile(100) <= 2


def test_service_drain_finishes_queued_then_refuses(tiny_model):
    """The SIGTERM story: drain() completes queued + in-flight work, then
    the door is closed with the typed draining rejection."""
    from raft_stereo_tpu.serving import Overloaded, ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=4, max_wait_ms=60_000,
                                    iters=ITERS))
    try:
        futures = [svc.submit(lefts[0], rights[0]) for _ in range(3)]
        # nothing flushes on its own (max_wait is a minute); drain must
        assert svc.drain(timeout=120)
        for f in futures:
            assert np.isfinite(f.result(timeout=1).flow).all()
        with pytest.raises(Overloaded) as ei:
            svc.submit(lefts[0], rights[0])
        assert ei.value.draining
    finally:
        svc.close()


def test_serve_config_validation(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    with pytest.raises(ValueError, match="batch_mode"):
        ServeConfig(batch_mode="magic")
    with pytest.raises(ValueError, match="data_parallel"):
        ServeConfig(data_parallel=0)
    with pytest.raises(ValueError, match="exceeds"):
        StereoService(cfg, variables, ServeConfig(data_parallel=512))


def test_service_stack_mode_close_to_solo(tiny_model):
    """Stack mode (one batched dispatch, batch-padded to max_batch) stays
    within the documented cross-batch-size reassociation drift."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    lefts, rights = _pairs(3)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=4, max_wait_ms=50,
                                   batch_mode="stack", iters=ITERS)) as svc:
        futures = [svc.submit(l, r) for l, r in zip(lefts, rights)]
        for (l, r), f in zip(zip(lefts, rights), futures):
            res = f.result(timeout=120)
            solo_flow, _ = solo(l, r)
            np.testing.assert_allclose(res.flow, solo_flow, atol=5e-4)


def test_service_data_parallel_workers(tiny_model):
    """Multiple device workers (the 8 virtual CPU devices) serve the same
    traffic with the same chain-mode parity."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    lefts, rights = _pairs(4)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, max_wait_ms=5.0,
                                   data_parallel=2, iters=ITERS)) as svc:
        assert len(svc.devices) == 2
        futures = [svc.submit(l, r) for l, r in zip(lefts, rights)]
        for (l, r), f in zip(zip(lefts, rights), futures):
            res = f.result(timeout=120)
            solo_flow, _ = solo(l, r)
            assert np.array_equal(res.flow, solo_flow)


def test_serve_cli_builds_service_from_checkpoint(tiny_model, tmp_path):
    """cli.serve: argparse -> checkpoint load -> configured service (the
    raft-serve console path minus the blocking HTTP loop)."""
    from raft_stereo_tpu.cli.serve import build_parser, build_service
    from raft_stereo_tpu.training.checkpoint import save_weights

    cfg, variables = tiny_model
    path = str(tmp_path / "ckpt")
    save_weights(path, cfg, variables["params"],
                 variables.get("batch_stats"))
    args = build_parser().parse_args(
        ["--restore_ckpt", path, "--valid_iters", str(ITERS),
         "--max_batch", "2", "--max_wait_ms", "3", "--max_queue", "8",
         "--deadline_ms", "60000"])
    svc = build_service(args)
    try:
        assert svc.serve_cfg.max_batch == 2
        assert svc.serve_cfg.default_deadline_ms == 60000
        lefts, rights = _pairs(1)
        res = svc.infer(lefts[0], rights[0], timeout=120)
        assert res.flow.shape == (48, 64) and np.isfinite(res.flow).all()
    finally:
        svc.close()


# ------------------------------------------------------------------ http
@pytest.fixture()
def http_server(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=2, max_wait_ms=5.0,
                                    iters=ITERS))
    server = StereoHTTPServer(svc, port=0).start()
    yield server
    server.shutdown()
    svc.close()


def _post(url, body, content_type="application/x-npz", headers=()):
    req = urllib.request.Request(url, data=body, method="POST")
    req.add_header("Content-Type", content_type)
    for k, v in headers:
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_http_disparity_npz_to_npy_and_metrics(http_server, tiny_model):
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)

    # Before any traffic: healthz answers, last-batch age is null (an
    # idle-from-boot service is idle, not stale).
    with urllib.request.urlopen(http_server.url + "/healthz",
                                timeout=30) as resp:
        health = json.loads(resp.read())
    assert health["status"] == "ok"
    assert health["last_batch_age_s"] is None

    buf = io.BytesIO()
    np.savez(buf, left=lefts[0], right=rights[0])
    status, headers, body = _post(http_server.url + "/v1/disparity",
                                  buf.getvalue())
    assert status == 200
    disp = np.load(io.BytesIO(body))
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    assert np.array_equal(disp, -solo(lefts[0], rights[0])[0])
    assert "X-Batch-Size" in headers and "X-Queue-Wait-Ms" in headers

    with urllib.request.urlopen(http_server.url + "/metrics",
                                timeout=30) as resp:
        text = resp.read().decode()
    assert "serve_requests_completed_total 1" in text
    assert "serve_total_latency_seconds_count 1" in text
    assert "serve_last_batch_unix_seconds" in text

    # Satellite (ISSUE 4): healthz matches the train endpoint's shape —
    # status, queue depth, inflight count, last-batch age.
    with urllib.request.urlopen(http_server.url + "/healthz",
                                timeout=30) as resp:
        health = json.loads(resp.read())
    assert health["status"] == "ok" and health["devices"] == 1
    assert health["queue_depth"] == 0 and health["inflight"] == 0
    assert health["last_batch_age_s"] is not None
    assert 0 <= health["last_batch_age_s"] < 600
    assert health["anomalies"] == 0


def test_http_png_pair_roundtrip(http_server):
    from PIL import Image

    lefts, rights = _pairs(1)
    pair = np.concatenate([lefts[0], rights[0]], axis=1)  # side-by-side
    buf = io.BytesIO()
    Image.fromarray(pair).save(buf, format="PNG")
    status, _, body = _post(http_server.url + "/v1/disparity?format=png",
                            buf.getvalue(), content_type="image/png")
    assert status == 200
    png = np.asarray(Image.open(io.BytesIO(body)))
    assert png.dtype == np.uint16 and png.shape == (48, 64)

    # npy response for the same pair agrees with the 16-bit encoding
    status, _, body = _post(http_server.url + "/v1/disparity",
                            buf.getvalue(), content_type="image/png")
    disp = np.load(io.BytesIO(body))
    np.testing.assert_allclose(png / 256.0, np.clip(disp, 0, None),
                               atol=1 / 256.0)


def test_http_error_mapping(http_server):
    status, _, body = _post(http_server.url + "/v1/disparity", b"not an npz")
    assert status == 400 and b"error" in body
    status, _, _ = _post(http_server.url + "/nope", b"x")
    assert status == 404
    # malformed format parameter
    lefts, rights = _pairs(1)
    buf = io.BytesIO()
    np.savez(buf, left=lefts[0], right=rights[0])
    status, _, _ = _post(http_server.url + "/v1/disparity?format=tiff",
                         buf.getvalue())
    assert status == 400


# -------------------------------------------- request-path tracing (ISSUE 4)
def test_served_request_span_tree_under_full_sampling(tiny_model):
    """Acceptance: a served request under sampling=1.0 yields a span tree
    covering admission -> queue -> dispatch -> fetch whose export is valid
    Chrome trace-event JSON with the documented attributes."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.telemetry import to_chrome_trace

    cfg, variables = tiny_model
    lefts, rights = _pairs(2)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, max_wait_ms=30, iters=ITERS,
                                   trace_sample_rate=1.0)) as svc:
        futures = [svc.submit(l, r) for l, r in zip(lefts, rights)]
        results = [f.result(timeout=120) for f in futures]
        assert all(np.isfinite(r.flow).all() for r in results)
        spans = svc.tracer.spans()
        tracer = svc.tracer

    by_trace = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, {})[s.name] = s
    assert len(by_trace) == 2            # one trace per request
    for tree in by_trace.values():
        assert {"serve.request", "serve.admission", "serve.queue",
                "serve.dispatch", "serve.fetch",
                "serve.respond"} <= set(tree)
        root = tree["serve.request"]
        assert root.parent_id is None
        assert root.attrs["status"] == "ok"
        for name in ("serve.admission", "serve.queue", "serve.dispatch",
                     "serve.fetch", "serve.respond"):
            assert tree[name].parent_id == root.span_id, name
        # causality: admission -> queue -> dispatch -> fetch in time order
        assert (tree["serve.admission"].t_start <= tree["serve.queue"].t_start
                <= tree["serve.dispatch"].t_start
                <= tree["serve.fetch"].t_start)
        assert tree["serve.dispatch"].attrs["batch_size"] == 2
        assert tree["serve.dispatch"].attrs["bucket"] == "(64, 64)"
        assert "device" in tree["serve.dispatch"].attrs
        assert tree["serve.queue"].attrs["batch_size"] == 2

    chrome = json.loads(json.dumps(to_chrome_trace(spans)))
    xs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert {"serve.request", "serve.queue", "serve.dispatch",
            "serve.fetch"} <= {e["name"] for e in xs}
    assert all(e["dur"] >= 0 and e["ts"] > 0 for e in xs)

    # exemplars on the latency histograms point back at the sampled traces
    ex = [e["trace_id"] for e in svc.metrics.total_latency.exemplars()]
    assert set(ex) == set(by_trace)
    assert tracer.stats()["traces_sampled"] == 2


def test_serving_default_has_tracing_off(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=1, max_wait_ms=1.0,
                                   iters=ITERS)) as svc:
        assert not svc.tracer.enabled
        svc.infer(lefts[0], rights[0], timeout=120)
        assert svc.tracer.spans() == []
        assert svc.metrics.total_latency.exemplars() == []
    with pytest.raises(ValueError, match="trace_sample_rate"):
        ServeConfig(trace_sample_rate=1.5)


@pytest.fixture()
def debug_http_server(tiny_model, tmp_path):
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer
    from raft_stereo_tpu.telemetry import FlightRecorder

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=2, max_wait_ms=5.0,
                                    iters=ITERS, trace_sample_rate=1.0))
    recorder = FlightRecorder(str(tmp_path / "fr"), tracer=svc.tracer,
                              registry=svc.metrics.registry,
                              min_interval_s=0.0)
    server = StereoHTTPServer(svc, port=0, recorder=recorder).start()
    yield server
    server.shutdown()
    svc.close()


def test_http_debug_surface(debug_http_server):
    """GET /debug/spans (Chrome trace JSON), /debug/stacks, and GET/POST
    /debug/flightrecorder on the serving endpoint."""
    url = debug_http_server.url
    lefts, rights = _pairs(1)
    buf = io.BytesIO()
    np.savez(buf, left=lefts[0], right=rights[0])
    status, _, _ = _post(url + "/v1/disparity", buf.getvalue())
    assert status == 200

    with urllib.request.urlopen(url + "/debug/spans", timeout=30) as resp:
        chrome = json.loads(resp.read())
    names = {e["name"] for e in chrome["traceEvents"] if e["ph"] == "X"}
    assert {"serve.request", "serve.queue", "serve.dispatch",
            "serve.fetch"} <= names

    with urllib.request.urlopen(url + "/debug/spans?exemplars=1",
                                timeout=30) as resp:
        wrapped = json.loads(resp.read())
    assert wrapped["stats"]["traces_sampled"] >= 1
    assert "serve_total_latency_seconds" in wrapped["exemplars"]
    assert "traceEvents" in wrapped["trace"]

    with urllib.request.urlopen(url + "/debug/stacks", timeout=30) as resp:
        stacks = resp.read().decode()
    assert "stereo-worker-0" in stacks and "MainThread" in stacks

    with urllib.request.urlopen(url + "/debug/flightrecorder",
                                timeout=30) as resp:
        st = json.loads(resp.read())
    assert st["dumps"] == 0 and st["spans"]["ring_size"] >= 4

    req = urllib.request.Request(url + "/debug/flightrecorder", data=b"",
                                 method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        reply = json.loads(resp.read())
    assert reply["bundle"] is not None
    bundle_trace = json.load(
        open(reply["bundle"] + "/trace.json"))
    assert "traceEvents" in bundle_trace
    with urllib.request.urlopen(url + "/debug/flightrecorder",
                                timeout=30) as resp:
        st = json.loads(resp.read())
    assert st["dumps"] == 1 and st["last_trigger"] == "manual"


def test_serve_cli_wires_observability(tiny_model, tmp_path):
    """cli.serve: --trace_sample_rate/--watchdog/--event_log build the
    tracer + recorder + watchdog around the service."""
    from raft_stereo_tpu.cli.serve import (build_observability, build_parser,
                                           build_service)
    from raft_stereo_tpu.training.checkpoint import save_weights

    cfg, variables = tiny_model
    path = str(tmp_path / "ckpt")
    save_weights(path, cfg, variables["params"],
                 variables.get("batch_stats"))
    args = build_parser().parse_args(
        ["--restore_ckpt", path, "--valid_iters", str(ITERS),
         "--trace_sample_rate", "1.0", "--watchdog",
         "--event_log", str(tmp_path / "serve-events.jsonl"),
         "--flight_recorder_dir", str(tmp_path / "fr")])
    svc = build_service(args)
    events = recorder = watchdog = None
    try:
        assert svc.serve_cfg.trace_sample_rate == 1.0
        assert svc.tracer.enabled
        events, recorder, watchdog = build_observability(args, svc)
        assert recorder is not None and watchdog is not None
        lefts, rights = _pairs(1)
        svc.infer(lefts[0], rights[0], timeout=120)
        assert any(s.name == "serve.request" for s in svc.tracer.spans())
    finally:
        if watchdog is not None:
            watchdog.stop()
        if events is not None:
            events.close()
        svc.close()
