"""Serving-engine tests (tier-1, CPU).

Scheduler tests run against the bare ``BucketQueue`` — no JAX at all — so
bucket grouping, batch-size selection, continuous (immediate) dispatch,
deadline triage, shedding, and drain are exercised in milliseconds.
Engine tests run a REAL tiny model: the headline assertions are (a) every
batch-size bucket's response (1/2/4/8, and partial-occupancy
decompositions) matches the same image run alone through
``InferenceRunner`` — the batch-1 bucket **bitwise equal** (it compiles
the identical program; the old chain mode's contract) and batch N within
the documented ~1e-5 reassociation tolerance, (b) batching means FEWER
device dispatches than completed requests, and (c) a burst beyond capacity
sheds with the typed ``Overloaded`` while everything admitted completes.
"""

import io
import json
import threading
import time
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from raft_stereo_tpu.serving.batcher import (BucketQueue, DeadlineExceeded,
                                             Overloaded, Request,
                                             decompose_batch,
                                             pick_batch_size)
from raft_stereo_tpu.serving.engine import BucketPolicy
from raft_stereo_tpu.serving.metrics import MetricsRegistry, ServingMetrics

# Pure-XLA backend: the serving tests assert bitwise properties and must
# not depend on the Pallas kernels' CPU interpret path.
TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")
ITERS = 1


def _req(bucket=(64, 96), deadline_s=None):
    now = time.monotonic()
    return Request(bucket=bucket, payload=None, future=Future(),
                   t_enqueue=now,
                   deadline=None if deadline_s is None else now + deadline_s)


# ------------------------------------------------------- batch-size buckets
def test_pick_batch_size_selects_largest_filled_bucket():
    sizes = (1, 2, 4, 8)
    assert [pick_batch_size(d, sizes) for d in range(1, 10)] == [
        1, 2, 2, 4, 4, 4, 4, 8, 8]
    # partial batches dispatch at the next size DOWN, never padded up
    assert pick_batch_size(3, sizes) == 2
    assert pick_batch_size(7, sizes) == 4
    # capped ladders
    assert pick_batch_size(9, (1, 2)) == 2
    with pytest.raises(ValueError, match="include 1"):
        pick_batch_size(1, (2, 4))
    with pytest.raises(ValueError, match="depth"):
        pick_batch_size(0, sizes)


def test_decompose_batch_greedy_no_filler():
    sizes = (1, 2, 4, 8)
    assert decompose_batch(7, sizes) == [4, 2, 1]
    assert decompose_batch(8, sizes) == [8]
    assert decompose_batch(3, sizes) == [2, 1]
    assert decompose_batch(5, (1, 2)) == [2, 2, 1]
    assert sum(decompose_batch(13, sizes)) == 13


# ----------------------------------------------------------------- scheduler
def test_queue_pop_selects_batch_size_from_depth():
    q = BucketQueue(max_batch=8, batch_sizes=(1, 2, 4, 8), max_queue=16)
    reqs = [_req() for _ in range(7)]
    for r in reqs:
        q.submit(r)
    # depth 7 -> 4, then 2, then 1; FIFO order preserved throughout
    batches = [q.pop(timeout=5), q.pop(timeout=5), q.pop(timeout=5)]
    assert [len(b) for b in batches] == [4, 2, 1]
    assert [r for b in batches for r in b] == reqs
    assert q.depth == 0
    q.close()


def test_queue_groups_by_shape_bucket_oldest_first():
    q = BucketQueue(max_batch=8, batch_sizes=(1, 2, 4, 8), max_queue=16)
    a1, c1 = _req(bucket=(64, 96)), _req(bucket=(96, 128))
    a2, c2 = _req(bucket=(64, 96)), _req(bucket=(96, 128))
    for r in (a1, c1, a2, c2):      # interleaved submission
        q.submit(r)
    b1 = q.pop(timeout=5)            # oldest head: the (64, 96) bucket
    assert b1 == [a1, a2]
    b2 = q.pop(timeout=5)
    assert b2 == [c1, c2]
    q.close()


def test_queue_continuous_dispatch_no_timer_stall():
    """The idle-device regression pin (round 6's flush loop made requests
    age toward max_wait while the device sat idle): a blocked pop returns
    the moment a request is submitted, and a single queued request
    dispatches alone rather than waiting for batch-mates."""
    q = BucketQueue(max_batch=8, batch_sizes=(1, 2, 4, 8), max_queue=16)
    got = {}

    def consumer():
        t0 = time.monotonic()
        got["batch"] = q.pop(timeout=10)
        got["gap_s"] = time.monotonic() - got["batch"][0].t_enqueue
        got["wait_s"] = time.monotonic() - t0

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.2)                  # consumer is idle, queue empty
    q.submit(_req())
    t.join(timeout=5)
    assert got["batch"] is not None and len(got["batch"]) == 1
    assert got["gap_s"] < 0.15, \
        f"idle worker must pick up immediately, waited {got['gap_s']:.3f}s"
    # pop with nothing queued honors its timeout
    assert q.pop(timeout=0.05) is None
    q.close()


def test_queue_pause_stages_exact_depth():
    q = BucketQueue(max_batch=8, batch_sizes=(1, 2, 4, 8), max_queue=16)
    q.pause()
    for _ in range(5):
        q.submit(_req())
    assert q.pop(timeout=0.1) is None, "paused queue must not hand out work"
    q.resume()
    assert len(q.pop(timeout=5)) == 4
    assert len(q.pop(timeout=5)) == 1
    q.close()


def test_queue_deadline_rejection_at_pop():
    q = BucketQueue(max_batch=8, batch_sizes=(1, 2, 4, 8), max_queue=16)
    dead = _req(deadline_s=0.001)
    live = _req(deadline_s=30.0)
    q.submit(dead)
    q.submit(live)
    time.sleep(0.01)                 # let the deadline pass
    batch = q.pop(timeout=5)
    assert batch == [live], "expired request must be triaged out"
    with pytest.raises(DeadlineExceeded):
        dead.future.result(timeout=1)
    assert q.metrics.deadline_missed.value == 1
    # inflight counts only the live survivor
    assert q.metrics.inflight.value == 1
    q.close()


def test_queue_full_sheds_with_typed_overloaded():
    q = BucketQueue(max_batch=8, batch_sizes=(1, 2, 4, 8), max_queue=4)
    for _ in range(4):               # no consumer: the queue fills
        q.submit(_req())
    shed = 0
    for _ in range(6):
        try:
            q.submit(_req())
        except Overloaded as e:
            assert not e.draining
            shed += 1
    assert shed == 6, "bounded queue must shed past max_queue"
    assert q.metrics.rejected_queue_full.value == shed
    assert q.depth == 4
    q.close()


def test_queue_drain_waits_for_consumers_then_refuses():
    q = BucketQueue(max_batch=8, batch_sizes=(1, 2, 4, 8), max_queue=16)
    for _ in range(3):
        q.submit(_req())

    def consumer():
        while q.pop(timeout=1) is not None:
            pass

    t = threading.Thread(target=consumer)
    t.start()
    assert q.drain(timeout=5.0), "drain must wait out the queue"
    with pytest.raises(Overloaded) as ei:
        q.submit(_req())
    assert ei.value.draining
    assert q.metrics.rejected_draining.value == 1
    q.close()
    t.join(timeout=5)


def test_queue_close_fails_orphans():
    q = BucketQueue(max_batch=8, batch_sizes=(1, 2, 4, 8), max_queue=16)
    orphan = _req()
    q.submit(orphan)
    q.close()
    with pytest.raises(Overloaded):
        orphan.future.result(timeout=5.0)
    assert q.pop(timeout=0.1) is None, "closed queue wakes workers with None"


def test_queue_validates_batch_sizes():
    with pytest.raises(ValueError, match="include 1"):
        BucketQueue(max_batch=8, batch_sizes=(2, 4))
    with pytest.raises(ValueError, match="include 1"):
        BucketQueue(max_batch=1, batch_sizes=(2,))   # capped away entirely
    q = BucketQueue(max_batch=3, batch_sizes=(1, 2, 4, 8))
    assert q.sizes == (1, 2), "sizes cap at max_batch"
    q.close()


# ------------------------------------------------------------ bucket policy
def test_bucket_policy_static_is_reference_padding():
    p = BucketPolicy(grids=(32,))
    assert not p.adaptive
    assert p.bucket_for(48, 64) == (64, 64, 32)
    assert p.bucket_for(375, 1242) == (384, 1248, 32)
    # feedback is a no-op in static mode
    p.note((64, 64), real_px=1, dispatched_px=4096)
    assert p.bucket_for(48, 64) == (64, 64, 32)
    assert p.refined_buckets == ()


def test_bucket_policy_refines_on_measured_waste():
    reg = MetricsRegistry()
    c = reg.counter("refine_total", "refinements")
    p = BucketPolicy(grids=(128, 32), max_waste=0.10,
                     refinements_counter=c)
    assert p.adaptive
    # a new shape starts at the coarsest grid
    assert p.bucket_for(40, 70) == (128, 128, 128)
    # measured waste under the bound: bucket stays
    p.note((128, 128), real_px=15500, dispatched_px=16384)
    assert p.bucket_for(40, 70) == (128, 128, 128)
    # waste crosses the bound -> the bucket refines to the finer grid
    p.note((128, 128), real_px=2800, dispatched_px=16384)
    assert p.bucket_for(40, 70) == (64, 96, 32)
    assert p.refined_buckets == ((128, 128),)
    assert c.value == 1
    # the /32 floor is irreducible: waste there never re-routes
    p.note((64, 96), real_px=100, dispatched_px=6144)
    assert p.bucket_for(40, 70) == (64, 96, 32)
    with pytest.raises(ValueError, match="multiples"):
        BucketPolicy(grids=(48,))


# --------------------------------------------------------------- metrics
def test_metrics_exposition_and_percentiles():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "requests")
    g = reg.gauge("depth", "queue depth")
    h = reg.histogram("lat_seconds", "latency", buckets=(0.01, 0.1, 1.0))
    c.inc(3)
    g.set(7)
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    text = reg.render_text()
    assert "# TYPE reqs_total counter" in text
    assert "reqs_total 3" in text
    assert "depth 7" in text
    assert 'lat_seconds_bucket{le="0.01"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 4' in text
    assert "lat_seconds_count 4" in text
    assert h.percentile(50) == pytest.approx(np.percentile(
        [0.005, 0.05, 0.5, 5.0], 50))
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("reqs_total")
    # the standard serving instrument set renders as one scrape
    sm = ServingMetrics(max_batch=4)
    sm.admitted.inc()
    assert "serve_requests_admitted_total 1" in sm.render_text()


def test_metrics_dispatch_size_family():
    sm = ServingMetrics(max_batch=8)
    sm.observe_dispatch(4)
    sm.observe_dispatch(4)
    sm.observe_dispatch(1)
    assert sm.batches.value == 3
    assert sm.dispatches_at(4) == 2 and sm.dispatches_at(1) == 1
    assert sm.dispatches_at(8) == 0
    text = sm.render_text()
    assert 'serve_dispatches_total{batch="4"} 2' in text
    assert 'serve_dispatches_total{batch="1"} 1' in text


# ---------------------------------------------------------------- engine
@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


def _pairs(n, hw=(48, 64), seed=3):
    rng = np.random.default_rng(seed)
    lefts = [rng.integers(0, 255, hw + (3,), dtype=np.uint8).astype(np.uint8)
             for _ in range(n)]
    rights = [np.roll(l, -3, axis=1) for l in lefts]
    return lefts, rights


def _staged(svc, lefts, rights):
    """Submit all pairs with the queue paused, then release: the next pop
    sees the exact depth, so dispatch batch sizes are deterministic."""
    svc.queue.pause()
    futures = [svc.submit(l, r) for l, r in zip(lefts, rights)]
    svc.queue.resume()
    return [f.result(timeout=120) for f in futures]


def _assert_matches_solo(res, solo_flow, what=""):
    """The engine's parity contract per batch-size bucket: batch 1 runs
    the identical compiled program as the solo runner — bitwise equal (the
    reason the old chain semantics survive as the batch-1 bucket).  A
    batch-N executable reassociates reductions differently (~1e-5, the
    drift the round-6 stack mode documented), so N > 1 asserts the
    documented tolerance."""
    assert res.flow.shape == solo_flow.shape
    if res.batch_size == 1:
        assert np.array_equal(res.flow, solo_flow), \
            f"batch-1 bucket must be bitwise-equal to solo {what}"
    else:
        np.testing.assert_allclose(res.flow, solo_flow, atol=5e-4,
                                   err_msg=what)


def test_engine_batch1_bitwise_parity_with_solo_runner(tiny_model):
    """The acceptance property: the batch-1 bucket (the old chain mode)
    dispatches the identical compiled program solo InferenceRunner uses —
    responses are bitwise equal."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    lefts, rights = _pairs(2)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=8, iters=ITERS)) as svc:
        for l, r in zip(lefts, rights):
            res = svc.infer(l, r, timeout=120)   # sequential -> batch 1
            solo_flow, _ = solo(l, r)
            assert res.batch_size == 1
            assert res.flow.shape == solo_flow.shape == (48, 64)
            assert np.array_equal(res.flow, solo_flow), \
                "batch-1 bucket must be bitwise-equal to solo inference"
            assert res.queue_wait_s >= 0 and res.total_s > 0
            np.testing.assert_array_equal(res.disparity, -res.flow)


def test_engine_bucket_ladder_parity_with_solo(tiny_model):
    """Satellite: every batch-size bucket (1/2/4/8) matches solo inference
    — batch 1 bitwise, batch N within the documented reassociation
    tolerance — and each staged burst runs as ONE dispatch of exactly
    that size."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    lefts, rights = _pairs(8)
    expect = [np.array(solo(l, r)[0]) for l, r in zip(lefts, rights)]
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=8, iters=ITERS)) as svc:
        assert svc.queue.sizes == (1, 2, 4, 8)
        for k in (1, 2, 4, 8):
            before = svc.metrics.dispatches_at(k)
            results = _staged(svc, lefts[:k], rights[:k])
            assert [r.batch_size for r in results] == [k] * k
            assert svc.metrics.dispatches_at(k) == before + 1
            for i, (res, want) in enumerate(zip(results, expect[:k])):
                _assert_matches_solo(res, want, f"batch-{k} result {i}")


def test_engine_partial_occupancy_decomposes_no_filler(tiny_model):
    """Satellite: a partial batch dispatches at the next size down (3 ->
    2+1, 7 -> 4+2+1) instead of pow2-padding — fewer dispatches than
    requests, zero filler frames, every result matching solo."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    lefts, rights = _pairs(7)
    expect = [np.array(solo(l, r)[0]) for l, r in zip(lefts, rights)]
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=8, iters=ITERS)) as svc:
        d0 = svc.metrics.batches.value
        results = _staged(svc, lefts[:3], rights[:3])
        assert sorted(r.batch_size for r in results) == [1, 2, 2]
        assert svc.metrics.batches.value - d0 == 2   # 3 requests, 2 dispatches
        d0 = svc.metrics.batches.value
        results = _staged(svc, lefts, rights)        # depth 7 -> 4+2+1
        assert svc.metrics.batches.value - d0 == 3
        assert sorted(r.batch_size for r in results) == [1, 2, 2, 4, 4, 4, 4]
        for i, (res, want) in enumerate(zip(results, expect)):
            _assert_matches_solo(res, want, f"partial-occupancy result {i}")
        # the engine-level acceptance: dispatches < completed requests
        assert svc.metrics.batches.value < svc.metrics.completed.value
        # occupancy histogram counts every dispatch
        assert svc.metrics.batch_occupancy.count == svc.metrics.batches.value


def test_engine_dispatch_gap_regression(tiny_model):
    """Satellite: the idle-device queue-wait pathology is gone — a request
    arriving at an idle engine is picked up immediately; the retired
    max_wait_ms cannot stall it (round 6: queue-wait p95 ~4 s at offered
    1.91 Hz while the device sat idle between flushes)."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=8, iters=ITERS,
                                   max_wait_ms=60_000)) as svc:
        svc.infer(lefts[0], rights[0], timeout=120)  # absorb compile
        res = svc.infer(lefts[0], rights[0], timeout=120)
        assert res.queue_wait_s < 1.0, \
            f"idle engine must dispatch immediately, waited " \
            f"{res.queue_wait_s:.3f}s"


def test_service_buckets_mixed_shapes_and_unpads_exactly(tiny_model):
    """Different raw shapes that pad to one /32 bucket batch together and
    unpad back to their own sizes; a different bucket compiles separately."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    shapes = [(48, 64), (40, 56), (48, 96)]   # -> (64,64), (64,64), (64,96)
    rng = np.random.default_rng(11)
    pairs = [(rng.integers(0, 255, s + (3,), dtype=np.uint8),) * 2
             for s in shapes]
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=4, iters=ITERS)) as svc:
        assert svc.bucket_for((48, 64, 3)) == (64, 64)
        assert svc.bucket_for((40, 56, 3)) == (64, 64)
        assert svc.bucket_for((48, 96, 3)) == (64, 96)
        svc.queue.pause()
        futures = [svc.submit(l, r) for l, r in pairs]
        svc.queue.resume()
        results = [f.result(timeout=120) for f in futures]
        for (l, r), res, shape in zip(pairs, results, shapes):
            assert res.flow.shape == shape
            solo_flow, _ = solo(l, r)
            _assert_matches_solo(res, solo_flow)
        # metrics saw every stage: 2 dispatches (a 2 and a 1)
        m = svc.metrics
        assert m.completed.value == 3
        assert m.batches.value == 2
        assert m.queue_wait.count == 3 and m.total_latency.count == 3


def test_service_overload_burst_sheds_and_completes_admitted(tiny_model):
    """Acceptance: a burst of more requests than capacity hits the bounded
    queue — typed Overloaded for the overflow, completion for everything
    admitted, and the accounting adds up."""
    from raft_stereo_tpu.serving import Overloaded, ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, max_queue=4,
                                   iters=ITERS)) as svc:
        svc.infer(lefts[0], rights[0], timeout=120)   # warm the executable
        futures, shed = [], 0
        for _ in range(40):
            try:
                futures.append(svc.submit(lefts[0], rights[0]))
            except Overloaded:
                shed += 1
        assert shed > 0, "burst beyond max_queue must shed"
        results = [f.result(timeout=120) for f in futures]
        assert all(np.isfinite(r.flow).all() for r in results)
        m = svc.metrics
        assert m.admitted.value == 1 + len(futures)
        assert m.rejected_queue_full.value == shed
        assert m.completed.value == 1 + len(futures)
        assert m.batch_occupancy.count == m.batches.value
        # occupancy never exceeds the configured max_batch
        assert m.batch_occupancy.percentile(100) <= 2


def test_service_drain_finishes_queued_then_refuses(tiny_model):
    """The SIGTERM story: drain() completes queued + in-flight work, then
    the door is closed with the typed draining rejection."""
    from raft_stereo_tpu.serving import Overloaded, ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=4, iters=ITERS))
    try:
        futures = [svc.submit(lefts[0], rights[0]) for _ in range(3)]
        assert svc.drain(timeout=120)
        for f in futures:
            assert np.isfinite(f.result(timeout=1).flow).all()
        with pytest.raises(Overloaded) as ei:
            svc.submit(lefts[0], rights[0])
        assert ei.value.draining
    finally:
        svc.close()


def test_serve_config_validation(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    with pytest.raises(ValueError, match="include 1"):
        ServeConfig(batch_sizes=(2, 4))
    with pytest.raises(ValueError, match="data_parallel"):
        ServeConfig(data_parallel=0)
    with pytest.raises(ValueError, match="max_padding_waste"):
        ServeConfig(max_padding_waste=1.5)
    with pytest.raises(ValueError, match="multiple"):
        ServeConfig(bucket_grids=(48,))
    with pytest.raises(ValueError, match="multiple"):
        ServeConfig(shape_bucket=40)
    with pytest.raises(ValueError, match="exceeds"):
        StereoService(cfg, variables, ServeConfig(data_parallel=512))


def test_engine_adaptive_buckets_waste_feedback(tiny_model):
    """The waste feedback loop end to end: a wasteful shape starts at the
    coarse grid, the measured serve_bucket_*_pixels accounting crosses the
    threshold, and the NEXT request re-routes to the /32 floor bucket —
    with results identical either way (padding never changes unpadded
    numerics' shape contract)."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    rng = np.random.default_rng(5)
    left = rng.integers(0, 255, (40, 70, 3), dtype=np.uint8)
    right = np.roll(left, -3, axis=1)
    with StereoService(cfg, variables, ServeConfig(
            max_batch=2, iters=ITERS, adaptive_buckets=True,
            bucket_grids=(128, 32), max_padding_waste=0.10)) as svc:
        assert svc.bucket_for((40, 70, 3)) == (128, 128)   # coarse start
        r1 = svc.infer(left, right, timeout=120)
        # waste 1 - 2800/16384 ~= 83% > 10% -> the bucket refined
        assert svc.policy.refined_buckets == ((128, 128),)
        assert svc.metrics.bucket_refinements.value == 1
        assert svc.bucket_for((40, 70, 3)) == (64, 96)     # /32 floor
        r2 = svc.infer(left, right, timeout=120)
        assert r1.flow.shape == r2.flow.shape == (40, 70)
        assert np.isfinite(r2.flow).all()
        text = svc.metrics.render_text()
        assert 'serve_bucket_real_pixels_total{bucket="128x128"}' in text
        assert 'serve_bucket_real_pixels_total{bucket="64x96"}' in text


def test_service_data_parallel_workers(tiny_model):
    """Multiple device workers (the 8 virtual CPU devices) serve the same
    traffic with the same batch-1 parity."""
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    lefts, rights = _pairs(4)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, data_parallel=2,
                                   iters=ITERS)) as svc:
        assert len(svc.devices) == 2
        futures = [svc.submit(l, r) for l, r in zip(lefts, rights)]
        for (l, r), f in zip(zip(lefts, rights), futures):
            res = f.result(timeout=120)
            solo_flow, _ = solo(l, r)
            _assert_matches_solo(res, solo_flow)


def test_engine_prewarm_compiles_bucket_ladder(tiny_model):
    """prewarm builds every batch-size executable for a shape at boot (via
    the cost registry, so the records prove which rungs exist)."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    with StereoService(cfg, variables, ServeConfig(
            max_batch=4, iters=ITERS, cost_telemetry=True,
            warmup_shapes=((48, 64),))) as svc:
        keys = sorted(r.key for r in svc.costs.records())
        assert keys == ["serving.forward(64x64,b1)",
                        "serving.forward(64x64,b2)",
                        "serving.forward(64x64,b4)"]
        # the warm executables serve real traffic without recompiling
        lefts, rights = _pairs(2)
        results = _staged(svc, lefts, rights)
        assert [r.batch_size for r in results] == [2, 2]
        assert len(svc.costs.records()) == 3


def test_engine_donation_and_memory_analysis(tiny_model):
    """Satellite: image buffers are donated in the engine's bucket
    executables and the solo runner; the registry's memory_analysis record
    carries the donation accounting.  XLA only aliases a donated input to
    an output of the SAME byte size — the stereo forward's f32 flow can
    never reuse the uint8 image buffers, so its alias bytes are honestly
    0 and the saving is pinned on an aliasable executable through the
    same registry path (hbm_bytes drops by exactly the aliased output)."""
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.telemetry.costs import CompileRegistry

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)

    # (a) the registry records an aliasable donated executable's saving
    reg = CompileRegistry()
    donated = reg.instrument(
        jax.jit(lambda x: x * 2.0 + 1.0, donate_argnums=0),
        key="toy.donated", site="eval")
    plain = reg.instrument(jax.jit(lambda x: x * 2.0 + 1.0),
                           key="toy.plain", site="eval")
    np.testing.assert_array_equal(
        np.asarray(donated(jnp.ones((128, 128), jnp.float32))),
        np.asarray(plain(jnp.ones((128, 128), jnp.float32))))
    rd, rp = reg.get("toy.donated"), reg.get("toy.plain")
    out_bytes = rd.memory["output_size_in_bytes"]
    assert rd.donated_alias_bytes == out_bytes > 0, \
        "donated same-size output must alias the input buffer"
    assert rp.donated_alias_bytes == 0
    assert rd.hbm_bytes == rp.hbm_bytes - out_bytes, \
        "the HBM saving is exactly the aliased output allocation"

    # (b) the engine's bucket executables declare donation, record their
    # memory analysis, and stay bitwise-equal to a non-donating runner
    solo_nodonate = InferenceRunner(cfg, variables, iters=ITERS,
                                    donate_images=False)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, iters=ITERS,
                                   cost_telemetry=True)) as svc:
        assert svc.serve_cfg.donate_buffers
        res = svc.infer(lefts[0], rights[0], timeout=120)
        flow, _ = solo_nodonate(lefts[0], rights[0])
        assert np.array_equal(res.flow, flow), \
            "donation must not change numerics"
        rec = svc.compiled_cost((64, 64), batch=1)
        assert rec is not None and rec.memory is not None
        assert rec.memory["argument_size_in_bytes"] > 0
        assert rec.donated_alias_bytes == 0   # no same-size output exists
        assert rec.hbm_bytes == sum(
            rec.memory.get(f, 0) for f in
            ("argument_size_in_bytes", "output_size_in_bytes",
             "temp_size_in_bytes"))


def test_serve_cli_builds_service_from_checkpoint(tiny_model, tmp_path):
    """cli.serve: argparse -> checkpoint load -> configured engine (the
    raft-serve console path minus the blocking HTTP loop).  The retired
    --max_wait_ms flag is still accepted."""
    from raft_stereo_tpu.cli.serve import build_parser, build_service
    from raft_stereo_tpu.training.checkpoint import save_weights

    cfg, variables = tiny_model
    path = str(tmp_path / "ckpt")
    save_weights(path, cfg, variables["params"],
                 variables.get("batch_stats"))
    args = build_parser().parse_args(
        ["--restore_ckpt", path, "--valid_iters", str(ITERS),
         "--max_batch", "2", "--batch_sizes", "1,2,4", "--max_queue", "8",
         "--max_wait_ms", "3", "--deadline_ms", "60000"])
    svc = build_service(args)
    try:
        assert svc.serve_cfg.max_batch == 2
        assert svc.queue.sizes == (1, 2)     # capped at max_batch
        assert svc.serve_cfg.default_deadline_ms == 60000
        lefts, rights = _pairs(1)
        res = svc.infer(lefts[0], rights[0], timeout=120)
        assert res.flow.shape == (48, 64) and np.isfinite(res.flow).all()
    finally:
        svc.close()


# ------------------------------------------------------------------ http
@pytest.fixture()
def http_server(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=2, iters=ITERS))
    server = StereoHTTPServer(svc, port=0).start()
    yield server
    server.shutdown()
    svc.close()


def _post(url, body, content_type="application/x-npz", headers=()):
    req = urllib.request.Request(url, data=body, method="POST")
    req.add_header("Content-Type", content_type)
    for k, v in headers:
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_http_disparity_npz_to_npy_and_metrics(http_server, tiny_model):
    from raft_stereo_tpu.eval.runner import InferenceRunner

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)

    # Before any traffic: healthz answers, last-batch age is null (an
    # idle-from-boot service is idle, not stale).
    with urllib.request.urlopen(http_server.url + "/healthz",
                                timeout=30) as resp:
        health = json.loads(resp.read())
    assert health["status"] == "ok"
    assert health["last_batch_age_s"] is None

    buf = io.BytesIO()
    np.savez(buf, left=lefts[0], right=rights[0])
    status, headers, body = _post(http_server.url + "/v1/disparity",
                                  buf.getvalue())
    assert status == 200
    disp = np.load(io.BytesIO(body))
    solo = InferenceRunner(cfg, variables, iters=ITERS)
    assert np.array_equal(disp, -solo(lefts[0], rights[0])[0])
    assert "X-Batch-Size" in headers and "X-Queue-Wait-Ms" in headers

    with urllib.request.urlopen(http_server.url + "/metrics",
                                timeout=30) as resp:
        text = resp.read().decode()
    assert "serve_requests_completed_total 1" in text
    assert "serve_total_latency_seconds_count 1" in text
    assert "serve_last_batch_unix_seconds" in text
    assert 'serve_dispatches_total{batch="1"} 1' in text

    # healthz matches the train endpoint's shape — status, queue depth,
    # inflight count, last-batch age.
    with urllib.request.urlopen(http_server.url + "/healthz",
                                timeout=30) as resp:
        health = json.loads(resp.read())
    assert health["status"] == "ok" and health["devices"] == 1
    assert health["queue_depth"] == 0 and health["inflight"] == 0
    assert health["last_batch_age_s"] is not None
    assert 0 <= health["last_batch_age_s"] < 600
    assert health["anomalies"] == 0


def test_http_png_pair_roundtrip(http_server):
    from PIL import Image

    lefts, rights = _pairs(1)
    pair = np.concatenate([lefts[0], rights[0]], axis=1)  # side-by-side
    buf = io.BytesIO()
    Image.fromarray(pair).save(buf, format="PNG")
    status, _, body = _post(http_server.url + "/v1/disparity?format=png",
                            buf.getvalue(), content_type="image/png")
    assert status == 200
    png = np.asarray(Image.open(io.BytesIO(body)))
    assert png.dtype == np.uint16 and png.shape == (48, 64)

    # npy response for the same pair agrees with the 16-bit encoding
    status, _, body = _post(http_server.url + "/v1/disparity",
                            buf.getvalue(), content_type="image/png")
    disp = np.load(io.BytesIO(body))
    np.testing.assert_allclose(png / 256.0, np.clip(disp, 0, None),
                               atol=1 / 256.0)


def test_http_error_mapping(http_server):
    status, _, body = _post(http_server.url + "/v1/disparity", b"not an npz")
    assert status == 400 and b"error" in body
    status, _, _ = _post(http_server.url + "/nope", b"x")
    assert status == 404
    # malformed format parameter
    lefts, rights = _pairs(1)
    buf = io.BytesIO()
    np.savez(buf, left=lefts[0], right=rights[0])
    status, _, _ = _post(http_server.url + "/v1/disparity?format=tiff",
                         buf.getvalue())
    assert status == 400


# ------------------------------------------------ request-path tracing
def test_served_request_span_tree_under_full_sampling(tiny_model):
    """A served request under sampling=1.0 yields a span tree covering
    admission -> queue -> dispatch -> fetch whose export is valid Chrome
    trace-event JSON with the documented attributes."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.telemetry import to_chrome_trace

    cfg, variables = tiny_model
    lefts, rights = _pairs(2)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=2, iters=ITERS,
                                   trace_sample_rate=1.0)) as svc:
        results = _staged(svc, lefts, rights)   # one batch-2 dispatch
        assert all(np.isfinite(r.flow).all() for r in results)
        spans = svc.tracer.spans()
        tracer = svc.tracer

    by_trace = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, {})[s.name] = s
    assert len(by_trace) == 2            # one trace per request
    for tree in by_trace.values():
        assert {"serve.request", "serve.admission", "serve.queue",
                "serve.dispatch", "serve.fetch",
                "serve.respond"} <= set(tree)
        root = tree["serve.request"]
        assert root.parent_id is None
        assert root.attrs["status"] == "ok"
        for name in ("serve.admission", "serve.queue", "serve.dispatch",
                     "serve.fetch", "serve.respond"):
            assert tree[name].parent_id == root.span_id, name
        # causality: admission -> queue -> dispatch -> fetch in time order
        assert (tree["serve.admission"].t_start <= tree["serve.queue"].t_start
                <= tree["serve.dispatch"].t_start
                <= tree["serve.fetch"].t_start)
        assert tree["serve.dispatch"].attrs["batch_size"] == 2
        assert tree["serve.dispatch"].attrs["bucket"] == "(64, 64)"
        assert "device" in tree["serve.dispatch"].attrs
        assert tree["serve.queue"].attrs["batch_size"] == 2

    chrome = json.loads(json.dumps(to_chrome_trace(spans)))
    xs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert {"serve.request", "serve.queue", "serve.dispatch",
            "serve.fetch"} <= {e["name"] for e in xs}
    assert all(e["dur"] >= 0 and e["ts"] > 0 for e in xs)

    # exemplars on the latency histograms point back at the sampled traces
    ex = [e["trace_id"] for e in svc.metrics.total_latency.exemplars()]
    assert set(ex) == set(by_trace)
    assert tracer.stats()["traces_sampled"] == 2


def test_serving_default_has_tracing_off(tiny_model):
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    lefts, rights = _pairs(1)
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=1, iters=ITERS)) as svc:
        assert not svc.tracer.enabled
        svc.infer(lefts[0], rights[0], timeout=120)
        assert svc.tracer.spans() == []
        assert svc.metrics.total_latency.exemplars() == []
    with pytest.raises(ValueError, match="trace_sample_rate"):
        ServeConfig(trace_sample_rate=1.5)


@pytest.fixture()
def debug_http_server(tiny_model, tmp_path):
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer
    from raft_stereo_tpu.telemetry import FlightRecorder

    cfg, variables = tiny_model
    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=2, iters=ITERS,
                                    trace_sample_rate=1.0))
    recorder = FlightRecorder(str(tmp_path / "fr"), tracer=svc.tracer,
                              registry=svc.metrics.registry,
                              min_interval_s=0.0)
    server = StereoHTTPServer(svc, port=0, recorder=recorder).start()
    yield server
    server.shutdown()
    svc.close()


def test_http_debug_surface(debug_http_server):
    """GET /debug/spans (Chrome trace JSON), /debug/stacks, and GET/POST
    /debug/flightrecorder on the serving endpoint."""
    url = debug_http_server.url
    lefts, rights = _pairs(1)
    buf = io.BytesIO()
    np.savez(buf, left=lefts[0], right=rights[0])
    status, _, _ = _post(url + "/v1/disparity", buf.getvalue())
    assert status == 200

    with urllib.request.urlopen(url + "/debug/spans", timeout=30) as resp:
        chrome = json.loads(resp.read())
    names = {e["name"] for e in chrome["traceEvents"] if e["ph"] == "X"}
    assert {"serve.request", "serve.queue", "serve.dispatch",
            "serve.fetch"} <= names

    with urllib.request.urlopen(url + "/debug/spans?exemplars=1",
                                timeout=30) as resp:
        wrapped = json.loads(resp.read())
    assert wrapped["stats"]["traces_sampled"] >= 1
    assert "serve_total_latency_seconds" in wrapped["exemplars"]
    assert "traceEvents" in wrapped["trace"]

    with urllib.request.urlopen(url + "/debug/stacks", timeout=30) as resp:
        stacks = resp.read().decode()
    assert "stereo-worker-0" in stacks and "MainThread" in stacks

    with urllib.request.urlopen(url + "/debug/flightrecorder",
                                timeout=30) as resp:
        st = json.loads(resp.read())
    assert st["dumps"] == 0 and st["spans"]["ring_size"] >= 4

    req = urllib.request.Request(url + "/debug/flightrecorder", data=b"",
                                 method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        reply = json.loads(resp.read())
    assert reply["bundle"] is not None
    bundle_trace = json.load(
        open(reply["bundle"] + "/trace.json"))
    assert "traceEvents" in bundle_trace
    with urllib.request.urlopen(url + "/debug/flightrecorder",
                                timeout=30) as resp:
        st = json.loads(resp.read())
    assert st["dumps"] == 1 and st["last_trigger"] == "manual"


def test_serve_cli_wires_observability(tiny_model, tmp_path):
    """cli.serve: --trace_sample_rate/--watchdog/--event_log build the
    tracer + recorder + watchdog around the engine."""
    from raft_stereo_tpu.cli.serve import (build_observability, build_parser,
                                           build_service)
    from raft_stereo_tpu.training.checkpoint import save_weights

    cfg, variables = tiny_model
    path = str(tmp_path / "ckpt")
    save_weights(path, cfg, variables["params"],
                 variables.get("batch_stats"))
    args = build_parser().parse_args(
        ["--restore_ckpt", path, "--valid_iters", str(ITERS),
         "--trace_sample_rate", "1.0", "--watchdog",
         "--event_log", str(tmp_path / "serve-events.jsonl"),
         "--flight_recorder_dir", str(tmp_path / "fr")])
    svc = build_service(args)
    events = recorder = watchdog = None
    try:
        assert svc.serve_cfg.trace_sample_rate == 1.0
        assert svc.tracer.enabled
        events, recorder, watchdog = build_observability(args, svc)
        assert recorder is not None and watchdog is not None
        lefts, rights = _pairs(1)
        svc.infer(lefts[0], rights[0], timeout=120)
        assert any(s.name == "serve.request" for s in svc.tracer.spans())
    finally:
        if watchdog is not None:
            watchdog.stop()
        if events is not None:
            events.close()
        svc.close()
