"""Training runtime tests: loss semantics, schedule vs torch, sharded step,
checkpoint round-trip."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
from raft_stereo_tpu.training.loss import sequence_loss
from raft_stereo_tpu.training.optimizer import one_cycle_lr
from raft_stereo_tpu.training.state import create_train_state
from raft_stereo_tpu.training.step import make_train_step
from raft_stereo_tpu.parallel.mesh import make_mesh, shard_batch, replicate


# --------------------------------------------------------------------- loss
def _reference_sequence_loss(flow_preds, flow_gt, valid, loss_gamma=0.9,
                             max_flow=700.0):
    """NumPy transliteration of the reference semantics
    (train_stereo.py:35-69) for cross-checking."""
    n = len(flow_preds)
    gamma_adj = loss_gamma ** (15.0 / (n - 1))
    mag = np.abs(flow_gt)
    mask = (valid >= 0.5) & (mag < max_flow)
    loss = 0.0
    for i, pred in enumerate(flow_preds):
        w = gamma_adj ** (n - i - 1)
        loss += w * np.abs(pred - flow_gt)[mask].mean()
    epe = np.abs(flow_preds[-1] - flow_gt)[mask]
    return loss, {"epe": epe.mean(), "1px": (epe < 1).mean(),
                  "3px": (epe < 3).mean(), "5px": (epe < 5).mean()}


def test_sequence_loss_matches_reference_semantics(rng):
    iters, b, h, w = 5, 2, 8, 12
    preds = rng.normal(0, 5, (iters, b, h, w)).astype(np.float32)
    gt = rng.normal(0, 20, (b, h, w)).astype(np.float32)
    gt[0, 0, 0] = 900.0  # excluded by max_flow
    valid = (rng.uniform(size=(b, h, w)) > 0.3).astype(np.float32)

    loss, metrics = sequence_loss(jnp.asarray(preds), jnp.asarray(gt),
                                  jnp.asarray(valid))
    ref_loss, ref_metrics = _reference_sequence_loss(preds, gt, valid)
    np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
    for k in ref_metrics:
        np.testing.assert_allclose(float(metrics[k]), ref_metrics[k],
                                   rtol=1e-5, err_msg=k)


def test_sequence_loss_single_prediction():
    preds = jnp.ones((1, 1, 4, 4)) * 2.0
    gt = jnp.zeros((1, 4, 4))
    valid = jnp.ones((1, 4, 4))
    loss, metrics = sequence_loss(preds, gt, valid)
    np.testing.assert_allclose(float(loss), 2.0, rtol=1e-6)
    np.testing.assert_allclose(float(metrics["epe"]), 2.0, rtol=1e-6)
    assert float(metrics["3px"]) == 1.0 and float(metrics["1px"]) == 0.0


# ----------------------------------------------------------------- schedule
def test_one_cycle_matches_torch():
    """Golden test against torch.optim.lr_scheduler.OneCycleLR with the
    reference's exact arguments (train_stereo.py:72-77)."""
    torch = pytest.importorskip("torch")
    lr, steps = 2e-4, 400
    sched = one_cycle_lr(lr, steps + 100, pct_start=0.01)

    m = torch.nn.Linear(1, 1)
    opt = torch.optim.AdamW(m.parameters(), lr=lr)
    tsched = torch.optim.lr_scheduler.OneCycleLR(
        opt, lr, steps + 100, pct_start=0.01, cycle_momentum=False,
        anneal_strategy="linear")
    torch_lrs, ours = [], []
    for step in range(steps):
        torch_lrs.append(tsched.get_last_lr()[0])
        ours.append(float(sched(step)))
        opt.step()
        tsched.step()
    np.testing.assert_allclose(ours, torch_lrs, rtol=2e-2, atol=1e-7)


# --------------------------------------------------------------- train step
def _tiny_batch(rng, b=8, h=32, w=64):
    return {
        "image1": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32),
        "flow": jnp.asarray(rng.normal(0, 5, (b, h, w)), jnp.float32),
        "valid": jnp.ones((b, h, w), jnp.float32),
    }


@pytest.mark.slow
def test_train_step_single_device(rng):
    mcfg = RaftStereoConfig(n_gru_layers=2, hidden_dims=(64, 64))
    tcfg = TrainConfig(train_iters=2, num_steps=100)
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               image_shape=(1, 32, 64, 3))
    step_fn = make_train_step(tcfg, donate=False)
    batch = _tiny_batch(rng, b=2)
    state2, metrics = step_fn(state, batch)
    assert int(state2.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    # params actually moved
    diff = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), state.params,
        state2.params)
    assert max(jax.tree_util.tree_leaves(diff)) > 0


def test_remat_save_policies_bit_identical(rng):
    """config.remat_save only changes WHAT the backward recomputes, never
    the math: loss and updated params agree across save policies to
    executable-level reassociation (bit-exact on today's CPU XLA; compared
    with a tight allclose because different policies are different
    compiled programs).  The unknown-name case is rejected up front."""
    import dataclasses

    import pytest as _pytest

    with _pytest.raises(ValueError, match="remat_save"):
        RaftStereoConfig(remat_save=("corr_lookup", "nope"))

    base = RaftStereoConfig(n_gru_layers=2, hidden_dims=(32, 32),
                            fnet_dim=64, corr_levels=2, corr_radius=3)
    tcfg = TrainConfig(train_iters=2, num_steps=100)
    batch = _tiny_batch(rng, b=2)
    results = []
    for saves in (("corr_lookup",),
                  ("corr_lookup", "gru_gates", "motion_features")):
        mcfg = dataclasses.replace(base, remat_save=saves)
        state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                                   image_shape=(1, 32, 64, 3))
        state2, metrics = make_train_step(tcfg, donate=False)(state, batch)
        results.append((float(metrics["loss"]),
                        jax.tree_util.tree_leaves(state2.params)))
    np.testing.assert_allclose(results[0][0], results[1][0], rtol=1e-6)
    for a, b in zip(results[0][1], results[1][1], strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_train_step_sharded_matches_single(rng):
    """SPMD data-parallel step over an 8-device mesh produces the same
    update as the single-device step (the DataParallel-equivalence
    guarantee)."""
    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,))
    tcfg = TrainConfig(train_iters=2, num_steps=100)
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               image_shape=(1, 32, 64, 3))
    batch = _tiny_batch(rng, b=8)

    single = make_train_step(tcfg, donate=False)
    s1, m1 = single(state, batch)

    mesh = make_mesh(n_data=8)
    sharded = make_train_step(tcfg, mesh=mesh, donate=False)
    s2, m2 = sharded(replicate(state, mesh), shard_batch(batch, mesh))

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-5)
    flat1 = jax.tree_util.tree_leaves(s1.params)
    flat2 = jax.tree_util.tree_leaves(s2.params)
    # sharded psum reduces in a different order than the single-device sum;
    # bitwise equality is not expected, close agreement is.
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=1e-5)


# --------------------------------------------------------------- checkpoint
@pytest.mark.slow
def test_checkpoint_roundtrip(tmp_path, rng):
    from raft_stereo_tpu.training.checkpoint import (load_checkpoint,
                                                     load_weights,
                                                     save_checkpoint,
                                                     save_weights)

    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,))
    tcfg = TrainConfig(train_iters=1, num_steps=50)
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               image_shape=(1, 32, 64, 3))
    tree = {"params": state.params, "batch_stats": state.batch_stats,
            "opt_state": state.opt_state, "step": state.step}
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, mcfg, tree)
    cfg2, restored = load_checkpoint(path, target=tree)
    assert cfg2 == mcfg
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    wpath = str(tmp_path / "weights")
    save_weights(wpath, mcfg, state.params, state.batch_stats)
    cfg3, variables = load_weights(wpath)
    assert cfg3 == mcfg
    assert "params" in variables


@pytest.mark.slow
def test_sigterm_checkpoints_and_resumes(tmp_path, rng):
    """Preemption safety: SIGTERM mid-training stops at the next step
    boundary with a resumable full-state checkpoint."""
    import os

    from raft_stereo_tpu.data.loader import StereoLoader
    from raft_stereo_tpu.training.train_loop import train

    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), fnet_dim=64)
    tcfg = TrainConfig(batch_size=2, train_iters=1, num_steps=100,
                       image_size=(32, 64), validation_frequency=10_000,
                       data_parallel=1)
    loader = StereoLoader(_SyntheticDataset(send_signal=True), batch_size=2,
                          num_workers=0, shuffle=False)
    ckpt_dir = str(tmp_path / "ckpt")
    state = train(mcfg, tcfg, name="sig", checkpoint_dir=ckpt_dir,
                  log_dir=str(tmp_path / "runs"), loader=loader,
                  use_mesh=False)
    stopped_at = int(state.step)
    assert 0 < stopped_at < 100, "run must stop early on SIGTERM"

    # resume exactly from the signal checkpoint and run a couple more steps
    loader2 = StereoLoader(_SyntheticDataset(), batch_size=2, num_workers=0,
                           shuffle=False)
    tcfg2 = TrainConfig(batch_size=2, train_iters=1,
                        num_steps=stopped_at + 2, image_size=(32, 64),
                        validation_frequency=10_000, data_parallel=1)
    state2 = train(mcfg, tcfg2, name="sig2", checkpoint_dir=ckpt_dir,
                   log_dir=str(tmp_path / "runs2"), loader=loader2,
                   restore=os.path.join(ckpt_dir, "sig"), use_mesh=False)
    assert int(state2.step) == stopped_at + 2


class _SyntheticDataset:
    """4 constant samples; with ``send_signal`` raises SIGTERM while decoding
    sample 1 of epoch 1 — the 3rd batch at batch_size=2, so training stops
    deterministically at step 2."""

    def __init__(self, send_signal=False):
        self.send_signal = send_signal

    def __len__(self):
        return 4

    def __getitem__(self, i, epoch=0):
        if self.send_signal and epoch >= 1 and i == 1:
            import os
            import signal
            os.kill(os.getpid(), signal.SIGTERM)
        img = np.full((32, 64, 3), float(i), np.float32)
        return {"image1": img, "image2": img,
                "flow": np.full((32, 64), -2.0, np.float32),
                "valid": np.ones((32, 64), np.float32)}


def test_train_rejects_more_corr_shards_than_devices():
    from raft_stereo_tpu.training.train_loop import train
    with pytest.raises(ValueError, match="exceeds"):
        train(RaftStereoConfig(corr_w2_shards=len(jax.devices()) * 2),
              TrainConfig(batch_size=2, num_steps=1))


def test_merge_warm_start_config_splits_arch_from_execution():
    """ADVICE.md round-5 medium: a weights-only warm start takes the
    ARCHITECTURE from the checkpoint but the EXECUTION-level fields
    (sharding, precision, backends, remat) from the caller — train() built
    the mesh and sharding contexts from the caller's config, so adopting
    the checkpoint's rows_shards/corr_w2_shards wholesale could demand a
    mesh axis the mesh lacks (and silently discarded CLI overrides)."""
    from raft_stereo_tpu.training.train_loop import merge_warm_start_config

    caller = RaftStereoConfig(hidden_dims=(48, 48, 48), corr_backend="reg",
                              mixed_precision=False, rows_shards=1)
    ckpt = RaftStereoConfig(hidden_dims=(32, 32, 32), n_gru_layers=2,
                            corr_backend="reg_fused", mixed_precision=True,
                            rows_shards=2, rows_gru=True, slow_fast_gru=True)
    merged = merge_warm_start_config(caller, ckpt)
    # weight-shaping fields: checkpoint's
    assert merged.hidden_dims == (32, 32, 32)
    assert merged.n_gru_layers == 2
    # execution-level fields: caller's (the mesh was built from these)
    assert merged.rows_shards == 1 and not merged.rows_gru
    assert not merged.mixed_precision and not merged.slow_fast_gru
    assert merged.corr_backend == "reg"


def test_warm_start_keeps_caller_execution_config(tmp_path):
    """End-to-end regression for the same finding: --warm_start from an
    orbax checkpoint saved under different execution settings runs with
    the caller's execution config and the checkpoint's architecture.  The
    run's own final checkpoint embeds the authoritative model_cfg, so it
    is the observation channel (num_steps=0 exercises the restore branch
    without a train step)."""
    import os

    from raft_stereo_tpu.data.loader import StereoLoader
    from raft_stereo_tpu.training.checkpoint import (load_checkpoint,
                                                     save_weights)
    from raft_stereo_tpu.training.train_loop import train

    ckpt_cfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,),
                                fnet_dim=64, corr_backend="reg",
                                mixed_precision=True, slow_fast_gru=True)
    state = create_train_state(ckpt_cfg, TrainConfig(train_iters=1),
                               jax.random.PRNGKey(0),
                               image_shape=(1, 32, 64, 3))
    wpath = str(tmp_path / "w")
    save_weights(wpath, ckpt_cfg, state.params, state.batch_stats)

    # caller asks for a DIFFERENT architecture (ignored — checkpoint wins)
    # and different execution settings (honored — mesh was built from them)
    caller_cfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(48,),
                                  fnet_dim=64, corr_backend="reg",
                                  mixed_precision=False)
    tcfg = TrainConfig(batch_size=2, train_iters=1, num_steps=0,
                       image_size=(32, 64), validation_frequency=1_000,
                       data_parallel=1)
    loader = StereoLoader(_SyntheticDataset(), batch_size=2, num_workers=0,
                          shuffle=False)
    final = train(caller_cfg, tcfg, name="ws",
                  checkpoint_dir=str(tmp_path / "ck"),
                  log_dir=str(tmp_path / "runs"), loader=loader,
                  restore=wpath, warm_start=True, use_mesh=False)
    assert int(final.step) == 0
    cfg, _ = load_checkpoint(os.path.join(str(tmp_path / "ck"), "ws"))
    assert cfg.hidden_dims == (32,)            # architecture: checkpoint's
    assert not cfg.mixed_precision             # execution: caller's
    assert not cfg.slow_fast_gru


def test_legacy_convzr_checkpoint_migrates(tmp_path):
    """Checkpoints saved before the convz/convr -> convzr gate fusion
    restore transparently: the loader retries against the split-gate layout
    and merges the halves back (params AND AdamW moment subtrees)."""
    from raft_stereo_tpu.training import checkpoint as ckpt
    from raft_stereo_tpu.training.checkpoint import (_merge_convzr,
                                                     _split_convzr)
    from raft_stereo_tpu.training.state import create_train_state

    mcfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), corr_levels=2,
                            fnet_dim=64)
    tcfg = TrainConfig(batch_size=2, train_iters=1, num_steps=10,
                       image_size=(32, 64))
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               (1, 32, 64, 3))
    tree = {"params": jax.device_get(state.params),
            "batch_stats": jax.device_get(state.batch_stats) or {},
            "opt_state": jax.device_get(state.opt_state),
            "step": np.asarray(0)}

    # Simulate a pre-fusion checkpoint: save the SPLIT layout.
    legacy_tree = _split_convzr(tree)
    flat = jax.tree_util.tree_leaves_with_path(legacy_tree["params"])
    assert any("convz" in jax.tree_util.keystr(p) for p, _ in flat)
    path = str(tmp_path / "legacy")
    ckpt.save_checkpoint(path, mcfg, legacy_tree)

    _, restored = ckpt.load_checkpoint(path, target=tree)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(tree),
            jax.tree_util.tree_leaves_with_path(restored)):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(pa))

    # Raw (targetless) restores migrate too, and split/merge round-trips.
    _, raw = ckpt.load_checkpoint(path)
    flat_raw = jax.tree_util.tree_leaves_with_path(raw["params"])
    assert not any("convz'" in jax.tree_util.keystr(p) for p, _ in flat_raw)
    merged = _merge_convzr(_split_convzr(tree))
    for (pa, a), (pb, b) in zip(jax.tree_util.tree_leaves_with_path(tree),
                                jax.tree_util.tree_leaves_with_path(merged)):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_compact_upload_batch_dtypes(rng):
    """TrainConfig.compact_upload ships fp16 flow + uint8 valid on the
    wire; the step casts back to f32 on device.  Lock that (a) the step
    accepts the compact dtypes, (b) the result differs from the f32-GT
    step only by fp16 GT rounding (worst ulp 0.125 px below 256 px),
    (c) the compact path is deterministic."""
    mcfg = RaftStereoConfig(n_gru_layers=2, hidden_dims=(64, 64))
    tcfg = TrainConfig(train_iters=2, num_steps=100)
    state = create_train_state(mcfg, tcfg, jax.random.PRNGKey(0),
                               image_shape=(1, 32, 64, 3))
    step_fn = make_train_step(tcfg, donate=False)
    batch = _tiny_batch(rng, b=2)
    batch["valid"] = jnp.asarray(
        np.random.default_rng(0).random((2, 32, 64)) > 0.3, jnp.float32)
    compact = dict(batch,
                   flow=jnp.asarray(np.asarray(batch["flow"]), jnp.float16),
                   valid=jnp.asarray(np.asarray(batch["valid"]), jnp.uint8))
    _, m32 = step_fn(state, batch)
    _, m16 = step_fn(state, compact)
    _, m16b = step_fn(state, {k: jnp.array(v) for k, v in compact.items()})
    assert float(m16["loss"]) == float(m16b["loss"])  # deterministic
    assert abs(float(m16["loss"]) - float(m32["loss"])) < 1e-2
