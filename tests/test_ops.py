"""Unit tests for core ops, cross-checked against torch's exact semantics
(the reference implementation's substrate) where applicable."""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from raft_stereo_tpu.ops import (
    InputPadder, avg_pool2d, convex_upsample, coords_grid_x, interp_like,
    linear_sampler_1d, linear_sampler_1d_features, pool2x,
    resize_bilinear_align_corners, upsample_flow_bilinear,
)


def test_coords_grid_x():
    g = coords_grid_x(2, 3, 5)
    assert g.shape == (2, 3, 5)
    np.testing.assert_array_equal(np.asarray(g[1, 2]), np.arange(5.0))


class TestLinearSampler1D:
    def test_hand_values(self):
        vol = jnp.array([[0.0, 10.0, 20.0, 30.0]])
        x = jnp.array([[0.0, 0.5, 2.25, 3.0]])
        out = linear_sampler_1d(vol, x)
        np.testing.assert_allclose(np.asarray(out), [[0.0, 5.0, 22.5, 30.0]])

    def test_zero_padding_outside(self):
        vol = jnp.array([[1.0, 2.0, 3.0]])
        x = jnp.array([[-1.0, -0.5, 2.5, 3.5]])
        out = linear_sampler_1d(vol, x)
        # -0.5: tap at -1 is zero, tap at 0 has weight 0.5 -> 0.5
        # 2.5: tap at 2 weight .5 (=1.5), tap at 3 zero -> 1.5
        np.testing.assert_allclose(np.asarray(out), [[0.0, 0.5, 1.5, 0.0]])

    def test_matches_grid_sample(self, rng):
        """Reference lookup semantics: grid_sample on an H==1 image with
        align_corners=True and zeros padding (core/utils/utils.py:59-73)."""
        B, W2, K = 3, 17, 9
        vol = rng.standard_normal((B, 1, 1, W2)).astype(np.float32)  # NCHW, H=1
        x = (rng.uniform(-2, W2 + 1, size=(B, 1, K))).astype(np.float32)

        xgrid = 2 * torch.from_numpy(x) / (W2 - 1) - 1
        grid = torch.stack([xgrid, torch.zeros_like(xgrid)], dim=-1)
        want = F.grid_sample(torch.from_numpy(vol), grid, align_corners=True)
        want = want.numpy()[:, 0]  # (B, 1, K)

        got = linear_sampler_1d(jnp.asarray(vol[:, 0]), jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


class TestResize:
    @pytest.mark.parametrize("src,dst", [((6, 8), (12, 16)), ((7, 5), (3, 9)),
                                         ((4, 4), (4, 4)), ((5, 6), (1, 1))])
    def test_matches_torch_interpolate(self, rng, src, dst):
        x = rng.standard_normal((2, *src, 3)).astype(np.float32)
        want = F.interpolate(torch.from_numpy(x).permute(0, 3, 1, 2),
                             size=dst, mode="bilinear", align_corners=True)
        want = want.permute(0, 2, 3, 1).numpy()
        got = resize_bilinear_align_corners(jnp.asarray(x), dst)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)

    def test_interp_like(self, rng):
        x = jnp.asarray(rng.standard_normal((1, 4, 4, 2)).astype(np.float32))
        dest = jnp.zeros((1, 8, 6, 5))
        assert interp_like(x, dest).shape == (1, 8, 6, 2)

    def test_upflow(self, rng):
        """Reference: core/utils/utils.py:82-84 (upflow8 = resize + scale)."""
        f = rng.standard_normal((1, 3, 4, 1)).astype(np.float32)
        want = 8 * F.interpolate(torch.from_numpy(f).permute(0, 3, 1, 2),
                                 size=(24, 32), mode="bilinear",
                                 align_corners=True)
        got = upsample_flow_bilinear(jnp.asarray(f), 8)
        np.testing.assert_allclose(np.asarray(got)[..., 0],
                                   want.numpy()[:, 0], rtol=1e-5, atol=1e-5)


class TestPooling:
    def test_pool2x_matches_torch(self, rng):
        x = rng.standard_normal((2, 7, 9, 4)).astype(np.float32)
        want = F.avg_pool2d(torch.from_numpy(x).permute(0, 3, 1, 2), 3,
                            stride=2, padding=1).permute(0, 2, 3, 1).numpy()
        got = pool2x(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)

    def test_avg_pool2d_matches_torch(self, rng):
        x = rng.standard_normal((1, 16, 16, 2)).astype(np.float32)
        want = F.avg_pool2d(torch.from_numpy(x).permute(0, 3, 1, 2), 5,
                            stride=4, padding=1).permute(0, 2, 3, 1).numpy()
        got = avg_pool2d(jnp.asarray(x), (5, 5), (4, 4), ((1, 1), (1, 1)))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)

    @pytest.mark.slow
    def test_feature_sampler_agrees_with_scalar_sampler(self, rng):
        """linear_sampler_1d_features must stay in sync with
        linear_sampler_1d (same boundary semantics)."""
        fmap = rng.standard_normal((2, 3, 11, 4)).astype(np.float32)
        x = rng.uniform(-2, 13, size=(2, 3, 5, 7)).astype(np.float32)
        got = linear_sampler_1d_features(jnp.asarray(fmap), jnp.asarray(x))
        # scalar sampler per feature channel
        vol = jnp.moveaxis(jnp.asarray(fmap), -1, 0)       # (D,B,H,W)
        for d in range(fmap.shape[-1]):
            want = linear_sampler_1d(vol[d][:, :, None, :],
                                     jnp.asarray(x))        # (B,H,W1,K)
            np.testing.assert_allclose(np.asarray(got[..., d]),
                                       np.asarray(want), rtol=1e-5, atol=1e-5)


class TestConvexUpsample:
    @pytest.mark.parametrize("factor", [2, 4, 8])
    def test_matches_reference_math(self, rng, factor):
        """Re-derive the reference's unfold/view/permute math in torch
        (core/raft_stereo.py:55-67) and compare."""
        B, H, W = 2, 5, 6
        flow_np = rng.standard_normal((B, H, W, 1)).astype(np.float32)
        mask_np = rng.standard_normal((B, H, W, 9 * factor * factor)).astype(np.float32)

        flow_t = torch.from_numpy(flow_np).permute(0, 3, 1, 2)  # (B,1,H,W)
        # torch mask layout is NCHW: (B, 9*f*f, H, W)
        mask_t = torch.from_numpy(mask_np).permute(0, 3, 1, 2)
        m = mask_t.view(B, 1, 9, factor, factor, H, W)
        m = torch.softmax(m, dim=2)
        up = F.unfold(factor * flow_t, [3, 3], padding=1)
        up = up.view(B, 1, 9, 1, 1, H, W)
        up = torch.sum(m * up, dim=2)
        up = up.permute(0, 1, 4, 2, 5, 3)
        want = up.reshape(B, 1, factor * H, factor * W).numpy()

        got = convex_upsample(jnp.asarray(flow_np), jnp.asarray(mask_np), factor)
        np.testing.assert_allclose(np.asarray(got)[..., 0], want[:, 0],
                                   rtol=1e-5, atol=1e-5)


class TestInputPadder:
    @pytest.mark.parametrize("mode", ["sintel", "other"])
    @pytest.mark.parametrize("hw", [(375, 1242), (448, 448), (13, 29)])
    def test_matches_torch_replicate(self, rng, mode, hw):
        x = rng.standard_normal((1, *hw, 3)).astype(np.float32)
        padder = InputPadder((1, *hw, 3), mode=mode, divis_by=32)
        (padded,) = padder.pad(jnp.asarray(x))
        assert padded.shape[1] % 32 == 0 and padded.shape[2] % 32 == 0

        # torch reference pads NCHW with (l, r, t, b)
        t = torch.from_numpy(x).permute(0, 3, 1, 2)
        want = F.pad(t, padder._pad, mode="replicate").permute(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(padded), want.numpy())

        back = padder.unpad(padded)
        np.testing.assert_allclose(np.asarray(back), x)
