"""Native C++ decoders (native/stereo_native.cpp via raft_stereo_tpu.native)
vs the pure-Python readers — bit-exact agreement on synthesized files.

If the toolchain/libpng is missing, ``native.available()`` is False and the
pipeline falls back to Python; these tests then skip (the fallback itself is
covered by test_data.py, which exercises the Python readers directly).
"""

import numpy as np
import pytest
from PIL import Image

from raft_stereo_tpu import native
from raft_stereo_tpu.data import frame_utils as fu

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native decoders not built")


def _write_pfm_nch(path, arr, scale_line):
    h, w = arr.shape[:2]
    c = 3 if arr.ndim == 3 else 1
    with open(path, "wb") as f:
        f.write((b"PF\n" if c == 3 else b"Pf\n") + f"{w} {h}\n".encode()
                + scale_line)
        dt = "<f4" if b"-" in scale_line else ">f4"
        f.write(np.flipud(arr).astype(dt).tobytes())


@pytest.mark.parametrize("channels", [1, 3])
@pytest.mark.parametrize("scale_line", [b"-1.0\n", b"1.0\n"])
def test_pfm_native_matches_python(tmp_path, rng, channels, scale_line):
    shape = (13, 17) if channels == 1 else (13, 17, 3)
    arr = rng.standard_normal(shape).astype(np.float32)
    p = str(tmp_path / "t.pfm")
    _write_pfm_nch(p, arr, scale_line)
    out_native = native.read_pfm(p)
    out_py = fu._read_pfm_py(p)
    np.testing.assert_array_equal(out_native, out_py)
    np.testing.assert_array_equal(out_native, arr)


def test_pfm_crlf_header_rejected_not_corrupted(tmp_path):
    """A CRLF-terminated scale line must decode correctly (tolerated \\r) —
    never silently shift the float data by one byte."""
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    p = str(tmp_path / "crlf.pfm")
    with open(p, "wb") as f:
        f.write(b"Pf\r\n4 3\r\n-1.0\r\n")
        f.write(np.flipud(arr).astype("<f4").tobytes())
    np.testing.assert_array_equal(native.read_pfm(p), arr)


def test_pfm_space_separator_rejected(tmp_path):
    """A non-newline header/data separator must error (fallback path), not
    decode shifted data."""
    arr = np.arange(4, dtype=np.float32).reshape(2, 2)
    p = str(tmp_path / "sp.pfm")
    with open(p, "wb") as f:
        f.write(b"Pf\n2 2\n-1.0 ")
        f.write(np.flipud(arr).astype("<f4").tobytes())
    with pytest.raises(ValueError):
        native.read_pfm(p)


def test_pfm_rejects_garbage(tmp_path):
    p = str(tmp_path / "bad.pfm")
    with open(p, "wb") as f:
        f.write(b"P6\n3 3\n255\n" + b"\x00" * 27)
    with pytest.raises(ValueError):
        native.read_pfm(p)


def test_pfm_truncated(tmp_path, rng):
    arr = rng.standard_normal((8, 8)).astype(np.float32)
    p = str(tmp_path / "t.pfm")
    _write_pfm_nch(p, arr, b"-1.0\n")
    with open(p, "r+b") as f:
        f.truncate(40)
    with pytest.raises(ValueError):
        native.read_pfm(p)


@pytest.mark.parametrize("mode", ["RGB", "L", "RGBA"])
def test_png8_native_matches_pil(tmp_path, rng, mode):
    channels = {"RGB": 3, "L": 1, "RGBA": 4}[mode]
    shape = (11, 9) if channels == 1 else (11, 9, channels)
    arr = rng.integers(0, 256, shape, dtype=np.uint8)
    p = str(tmp_path / "t.png")
    Image.fromarray(arr, mode=mode).save(p)
    out = native.read_png_rgb8(p)
    ref = np.asarray(Image.open(p))
    if ref.ndim == 2:
        ref = np.repeat(ref[..., None], 3, axis=-1)
    np.testing.assert_array_equal(out, ref[..., :3])


def test_png16_kitti_roundtrip(tmp_path, rng):
    disp = rng.uniform(0, 192, (7, 23)).astype(np.float32)
    disp[rng.uniform(size=disp.shape) < 0.3] = 0.0  # invalid pixels
    p = str(tmp_path / "d.png")
    fu.write_disp_kitti(p, disp)
    raw = native.read_png_gray16(p)
    assert raw.dtype == np.uint16
    got, valid = fu.read_disp_kitti(p)
    # write_disp_kitti encodes with astype(uint16) = truncation
    np.testing.assert_allclose(got, np.floor(disp * 256) / 256, atol=1e-6)
    np.testing.assert_array_equal(valid, got > 0)
    # and the native path agrees with PIL's decode of the same file
    np.testing.assert_array_equal(raw, np.asarray(Image.open(p)))


def test_png16_rejected_by_gray16_when_rgb(tmp_path, rng):
    arr = rng.integers(0, 256, (5, 5, 3), dtype=np.uint8)
    p = str(tmp_path / "t.png")
    Image.fromarray(arr).save(p)
    with pytest.raises(ValueError):
        native.read_png_gray16(p)
