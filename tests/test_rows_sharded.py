"""Row-sharded (context-parallel) trunk vs the ordinary _Trunk: identical
math, 1/N of the full-resolution activations per device.

Sharding runs on the virtual CPU mesh (conftest forces 8 devices), the same
strategy as the corr-sharded tests (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from raft_stereo_tpu.models.extractor import BasicEncoder, _Trunk
from raft_stereo_tpu.parallel.rows_sharded import rows_sharded_trunk_apply


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


@pytest.mark.parametrize("norm_fn", ["instance", "batch", "none"])
@pytest.mark.parametrize("n_shards", [2, 4])
def test_rows_sharded_matches_trunk(rng, norm_fn, n_shards):
    trunk = _Trunk(norm_fn, downsample=2, dtype=jnp.float32)
    h, w = 16 * n_shards, 32
    x = jnp.asarray(rng.uniform(-1, 1, (2, h, w, 3)), jnp.float32)
    variables = trunk.init(jax.random.PRNGKey(0), x)
    want = trunk.apply(variables, x)

    got = rows_sharded_trunk_apply(
        variables["params"], variables.get("batch_stats", {}),
        x, norm_fn, jnp.float32, mesh=_mesh(n_shards), halo=16)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_rows_sharded_feeds_encoder(rng):
    """The sharded trunk output slots into BasicEncoder's trunk_out hook
    (the same injection point the banded executor uses), producing the
    same feature maps as the plain fnet."""
    enc = BasicEncoder(output_dim=64, norm_fn="instance", downsample=2,
                       dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (1, 64, 32, 3)), jnp.float32)
    variables = enc.init(jax.random.PRNGKey(1), x)
    want = enc.apply(variables, x)

    trunk_out = rows_sharded_trunk_apply(
        variables["params"]["trunk"],
        variables.get("batch_stats", {}).get("trunk", {}),
        x, "instance", jnp.float32, mesh=_mesh(4), halo=16)
    got = enc.apply(variables, x, trunk_out=trunk_out)
    # trunk-level reassociation (~1e-6) amplified once through the 1x1
    # projection matmul
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_rows_sharded_validates_shapes(rng):
    from raft_stereo_tpu.models.extractor import _Trunk

    trunk = _Trunk("none", downsample=2, dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (1, 40, 32, 3)), jnp.float32)
    v = trunk.init(jax.random.PRNGKey(0), x)
    with pytest.raises(ValueError, match="divisible"):
        rows_sharded_trunk_apply(v["params"], {}, x, "none", jnp.float32,
                                 mesh=_mesh(4))
    # a slab shorter than the halo cannot be supplied by one ppermute
    x64 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 32, 3)), jnp.float32)
    with pytest.raises(ValueError, match="halo"):
        rows_sharded_trunk_apply(v["params"], {}, x64, "none", jnp.float32,
                                 mesh=_mesh(4), halo=32)
