"""Row-sharded (context-parallel) trunk vs the ordinary _Trunk: identical
math, 1/N of the full-resolution activations per device.

Sharding runs on the virtual CPU mesh (conftest forces 8 devices), the same
strategy as the corr-sharded tests (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from raft_stereo_tpu.models.extractor import BasicEncoder, _Trunk
from raft_stereo_tpu.parallel.rows_sharded import rows_sharded_trunk_apply


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


@pytest.mark.parametrize("norm_fn", ["instance", "batch", "none"])
@pytest.mark.parametrize("n_shards", [2, 4])
@pytest.mark.slow
def test_rows_sharded_matches_trunk(rng, norm_fn, n_shards):
    trunk = _Trunk(norm_fn, downsample=2, dtype=jnp.float32)
    h, w = 16 * n_shards, 32
    x = jnp.asarray(rng.uniform(-1, 1, (2, h, w, 3)), jnp.float32)
    variables = trunk.init(jax.random.PRNGKey(0), x)
    want = trunk.apply(variables, x)

    got = rows_sharded_trunk_apply(
        variables["params"], variables.get("batch_stats", {}),
        x, norm_fn, jnp.float32, mesh=_mesh(n_shards), halo=16)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_rows_sharded_feeds_encoder(rng):
    """The sharded trunk output slots into BasicEncoder's trunk_out hook
    (the same injection point the banded executor uses), producing the
    same feature maps as the plain fnet."""
    enc = BasicEncoder(output_dim=64, norm_fn="instance", downsample=2,
                       dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (1, 64, 32, 3)), jnp.float32)
    variables = enc.init(jax.random.PRNGKey(1), x)
    want = enc.apply(variables, x)

    trunk_out = rows_sharded_trunk_apply(
        variables["params"]["trunk"],
        variables.get("batch_stats", {}).get("trunk", {}),
        x, "instance", jnp.float32, mesh=_mesh(4), halo=16)
    got = enc.apply(variables, x, trunk_out=trunk_out)
    # trunk-level reassociation (~1e-6) amplified once through the 1x1
    # projection matmul
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_rows_sharded_validates_shapes(rng):
    from raft_stereo_tpu.models.extractor import _Trunk

    trunk = _Trunk("none", downsample=2, dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (1, 40, 32, 3)), jnp.float32)
    v = trunk.init(jax.random.PRNGKey(0), x)
    with pytest.raises(ValueError, match="divisible"):
        rows_sharded_trunk_apply(v["params"], {}, x, "none", jnp.float32,
                                 mesh=_mesh(4))
    # a slab shorter than the halo cannot be supplied by one ppermute
    x64 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 32, 3)), jnp.float32)
    with pytest.raises(ValueError, match="halo"):
        rows_sharded_trunk_apply(v["params"], {}, x64, "none", jnp.float32,
                                 mesh=_mesh(4), halo=32)


@pytest.mark.slow
def test_rows_sharded_model_matches_plain(rng):
    """Full model with rows_shards=4 under rows_sharding(mesh) vs the plain
    model — same params, near-identical disparity (fp reassociation only,
    amplified by the untrained GRU like the banded/sharded comparisons)."""
    import dataclasses

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.parallel.rows_sharded import rows_sharding

    img1 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    cfg = RaftStereoConfig(n_gru_layers=2, hidden_dims=(48, 48))
    model = RAFTStereo(cfg)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                   test_mode=True)
    _, up_ref = model.apply(v, img1, img2, iters=3, test_mode=True)

    cfg_r = dataclasses.replace(cfg, rows_shards=4)
    with rows_sharding(_mesh(4)):
        _, up_r = jax.jit(
            lambda v, a, b: RAFTStereo(cfg_r).apply(v, a, b, iters=3,
                                                    test_mode=True)
        )(v, img1, img2)
    np.testing.assert_allclose(np.asarray(up_r), np.asarray(up_ref),
                               rtol=1e-3, atol=5e-3)


def test_validation_hook_normalizes_sharded_cfg():
    """The periodic validator strips executor-sharding flags (it is
    single-device inference); architecture fields pass through."""
    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.eval.validate import single_device_cfg

    cfg = RaftStereoConfig(rows_shards=4, corr_w2_shards=2,
                           hidden_dims=(64, 64, 64))
    out = single_device_cfg(cfg)
    assert out.rows_shards == 1 and out.corr_w2_shards == 1
    assert out.hidden_dims == (64, 64, 64)
    plain = RaftStereoConfig()
    assert single_device_cfg(plain) is plain


def test_rows_shards_config_validation():
    import dataclasses

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    with pytest.raises(ValueError, match="at most one"):
        RaftStereoConfig(rows_shards=2, banded_encoder=True)

    # tracing without an active mesh raises with the fix-it instruction
    cfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), corr_levels=2,
                           fnet_dim=64, rows_shards=2)
    model = RAFTStereo(cfg)
    img = jnp.zeros((1, 32, 64, 3), jnp.float32)
    v = model.init(jax.random.PRNGKey(0), img, img, iters=1, test_mode=True)
    with pytest.raises(RuntimeError, match="rows_sharding"):
        model.apply(v, img, img, iters=1, test_mode=True)


@pytest.mark.slow
def test_rows_sharded_training_gradients_match(rng):
    """TRAINING scope: loss AND parameter gradients of the full model with
    rows_shards=2 on a (data=2, rows=2) mesh equal the single-device ones —
    gradient flow through the ppermute halo exchange and the all_gather-ed
    instance-norm moments is exact up to fp reassociation."""
    import dataclasses
    import functools

    from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.parallel.mesh import ROWS_AXIS, make_mesh, \
        replicate, shard_batch
    from raft_stereo_tpu.parallel.rows_sharded import rows_sharding
    from raft_stereo_tpu.training.loss import sequence_loss

    cfg = RaftStereoConfig(n_gru_layers=2, hidden_dims=(48, 48),
                           fnet_dim=96, corr_levels=2, corr_radius=3)
    cfg_rows = dataclasses.replace(cfg, rows_shards=2)
    img1 = jnp.asarray(rng.uniform(0, 255, (2, 64, 96, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (2, 64, 96, 3)), jnp.float32)
    flow = jnp.asarray(rng.uniform(-8, 0, (2, 64, 96)), jnp.float32)
    valid = jnp.ones((2, 64, 96), jnp.float32)

    model = RAFTStereo(cfg)
    variables = model.init(jax.random.PRNGKey(0), img1, img2, iters=1)

    batch_stats = variables.get("batch_stats", {})

    def loss_of(m):
        def f(params):
            preds = m.apply({"params": params, "batch_stats": batch_stats},
                            img1, img2, iters=2)
            loss, _ = sequence_loss(preds, flow, valid, loss_gamma=0.9,
                                    max_flow=700.0)
            return loss
        return f

    loss_ref, grads_ref = jax.value_and_grad(loss_of(model))(
        variables["params"])

    # Explicit replicated in/out shardings — the SUPPORTED training entry
    # (make_train_step pins them the same way).  A bare jit with
    # unannotated shardings over a multi-axis mesh leaves the auto axes'
    # placement to propagation and is not a supported way to take
    # gradients through the partial-manual shard_map.
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(n_data=2, n_corr=1, n_rows=2)  # 4 of the 8 CPU devices
    repl = NamedSharding(mesh, P())
    with rows_sharding(mesh, axis=ROWS_AXIS):
        loss_r, grads_r = jax.jit(
            jax.value_and_grad(loss_of(RAFTStereo(cfg_rows))),
            in_shardings=(repl,), out_shardings=(repl, repl),
        )(variables["params"])

    np.testing.assert_allclose(float(loss_r), float(loss_ref),
                               rtol=1e-4)
    flat_ref = jax.tree_util.tree_leaves_with_path(grads_ref)
    flat_r = dict(jax.tree_util.tree_leaves_with_path(grads_r))
    global_scale = max(float(np.max(np.abs(np.asarray(g))))
                       for _, g in flat_ref)
    skipped = 0
    for path, g_ref in flat_ref:
        g_r = np.asarray(flat_r[path])
        g_ref = np.asarray(g_ref)
        scale = float(np.max(np.abs(g_ref)))
        if scale < 1e-3 * global_scale:
            # conv biases directly feeding a shift-invariant norm have
            # IDENTICALLY ZERO true gradient; their computed values are
            # pure fp cancellation noise in both executors and cannot be
            # compared relatively.
            skipped += 1
            continue
        # Bulk-tight with bounded isolated outliers: 99% of a leaf's
        # entries must agree to 0.3% of the leaf's grad scale, no entry
        # may deviate past 3%.  Cotangent sums through the remat'd GRU,
        # the corr gather, and the convex-upsample softmax reassociate
        # differently under SPMD; observed noise is a handful of entries
        # at ~1-2% of scale — while the bug class this test exists for
        # (a mis-reduced collective) scales 67-100% of entries by an
        # integer factor and trips both bounds.
        rel = np.abs(g_r - g_ref) / scale
        keystr = jax.tree_util.keystr(path)
        assert float(np.quantile(rel, 0.99)) < 3e-3, \
            f"bulk grad mismatch at {keystr}: q99 {np.quantile(rel, 0.99)}"
        assert float(rel.max()) < 3e-2, \
            f"grad outlier at {keystr}: max rel-to-scale {rel.max()}"
    assert skipped < len(flat_ref) // 2, \
        f"too many near-zero-grad leaves skipped ({skipped})"


@pytest.mark.slow
def test_rows_sharded_train_loop_auto_wires(tmp_path, rng):
    """train() with rows_shards=2 builds the (data, corr, rows) mesh itself,
    holds the rows_sharding context, runs steps, and the periodic validator
    (single-device scope) normalizes the sharding flags instead of
    demanding a mesh."""
    import dataclasses

    from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
    from raft_stereo_tpu.training.train_loop import train

    cfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32, 32, 32),
                           fnet_dim=64, corr_levels=2, corr_radius=3,
                           rows_shards=2)
    tcfg = TrainConfig(batch_size=4, train_iters=2, valid_iters=2,
                       num_steps=2, image_size=(64, 96), data_parallel=2,
                       validation_frequency=2, seed=3)

    class Stream:
        def __iter__(self):
            gen = np.random.default_rng(7)
            while True:
                yield {
                    "image1": gen.integers(0, 256, (4, 64, 96, 3)).astype(
                        np.uint8),
                    "image2": gen.integers(0, 256, (4, 64, 96, 3)).astype(
                        np.uint8),
                    "flow": gen.uniform(-8, 0, (4, 64, 96)).astype(
                        np.float32),
                    "valid": np.ones((4, 64, 96), np.float32)}

    seen = {}

    def validate_fn(variables, model_cfg=None):
        seen["cfg"] = model_cfg
        return {"probe": 1.0}

    state = train(cfg, tcfg, name="rows", checkpoint_dir=str(tmp_path / "ck"),
                  log_dir=str(tmp_path / "runs"), loader=Stream(),
                  validate_fn=validate_fn)
    assert int(state.step) == 2
    assert seen["cfg"].rows_shards == 2  # authoritative cfg reaches the hook
    leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(state.params)]
    assert all(np.all(np.isfinite(l)) for l in leaves)

    # height not divisible by 4*rows_shards is rejected up front
    bad = dataclasses.replace(tcfg, image_size=(68, 96))
    with pytest.raises(ValueError, match="divisible"):
        train(cfg, bad, name="bad", checkpoint_dir=str(tmp_path / "ck2"),
              log_dir=str(tmp_path / "runs2"), loader=Stream())


def test_rows_sharded_two_axis_mesh(rng):
    """Rows sharded over 'data' while a 'corr' axis coexists on the same
    mesh — the precondition for composing with the W2-sharded volume."""
    from conftest import require_corr_mesh
    require_corr_mesh()
    from raft_stereo_tpu.parallel.mesh import make_mesh

    trunk = _Trunk("instance", downsample=2, dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (1, 64, 32, 3)), jnp.float32)
    v = trunk.init(jax.random.PRNGKey(0), x)
    want = np.asarray(trunk.apply(v, x))
    mesh = make_mesh(n_data=4, n_corr=2)  # 8 devices, two axes
    got = np.asarray(rows_sharded_trunk_apply(
        v["params"], v.get("batch_stats", {}), x, "instance", jnp.float32,
        mesh=mesh, axis="data", halo=16))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
