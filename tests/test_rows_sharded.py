"""Row-sharded (context-parallel) trunk vs the ordinary _Trunk: identical
math, 1/N of the full-resolution activations per device.

Sharding runs on the virtual CPU mesh (conftest forces 8 devices), the same
strategy as the corr-sharded tests (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from raft_stereo_tpu.models.extractor import BasicEncoder, _Trunk
from raft_stereo_tpu.parallel.rows_sharded import rows_sharded_trunk_apply


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


@pytest.mark.parametrize("norm_fn", ["instance", "batch", "none"])
@pytest.mark.parametrize("n_shards", [2, 4])
def test_rows_sharded_matches_trunk(rng, norm_fn, n_shards):
    trunk = _Trunk(norm_fn, downsample=2, dtype=jnp.float32)
    h, w = 16 * n_shards, 32
    x = jnp.asarray(rng.uniform(-1, 1, (2, h, w, 3)), jnp.float32)
    variables = trunk.init(jax.random.PRNGKey(0), x)
    want = trunk.apply(variables, x)

    got = rows_sharded_trunk_apply(
        variables["params"], variables.get("batch_stats", {}),
        x, norm_fn, jnp.float32, mesh=_mesh(n_shards), halo=16)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_rows_sharded_feeds_encoder(rng):
    """The sharded trunk output slots into BasicEncoder's trunk_out hook
    (the same injection point the banded executor uses), producing the
    same feature maps as the plain fnet."""
    enc = BasicEncoder(output_dim=64, norm_fn="instance", downsample=2,
                       dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (1, 64, 32, 3)), jnp.float32)
    variables = enc.init(jax.random.PRNGKey(1), x)
    want = enc.apply(variables, x)

    trunk_out = rows_sharded_trunk_apply(
        variables["params"]["trunk"],
        variables.get("batch_stats", {}).get("trunk", {}),
        x, "instance", jnp.float32, mesh=_mesh(4), halo=16)
    got = enc.apply(variables, x, trunk_out=trunk_out)
    # trunk-level reassociation (~1e-6) amplified once through the 1x1
    # projection matmul
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_rows_sharded_validates_shapes(rng):
    from raft_stereo_tpu.models.extractor import _Trunk

    trunk = _Trunk("none", downsample=2, dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (1, 40, 32, 3)), jnp.float32)
    v = trunk.init(jax.random.PRNGKey(0), x)
    with pytest.raises(ValueError, match="divisible"):
        rows_sharded_trunk_apply(v["params"], {}, x, "none", jnp.float32,
                                 mesh=_mesh(4))
    # a slab shorter than the halo cannot be supplied by one ppermute
    x64 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 32, 3)), jnp.float32)
    with pytest.raises(ValueError, match="halo"):
        rows_sharded_trunk_apply(v["params"], {}, x64, "none", jnp.float32,
                                 mesh=_mesh(4), halo=32)


@pytest.mark.slow
def test_rows_sharded_model_matches_plain(rng):
    """Full model with rows_shards=4 under rows_sharding(mesh) vs the plain
    model — same params, near-identical disparity (fp reassociation only,
    amplified by the untrained GRU like the banded/sharded comparisons)."""
    import dataclasses

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.parallel.rows_sharded import rows_sharding

    img1 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    cfg = RaftStereoConfig(n_gru_layers=2, hidden_dims=(48, 48))
    model = RAFTStereo(cfg)
    v = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                   test_mode=True)
    _, up_ref = model.apply(v, img1, img2, iters=3, test_mode=True)

    cfg_r = dataclasses.replace(cfg, rows_shards=4)
    with rows_sharding(_mesh(4)):
        _, up_r = jax.jit(
            lambda v, a, b: RAFTStereo(cfg_r).apply(v, a, b, iters=3,
                                                    test_mode=True)
        )(v, img1, img2)
    np.testing.assert_allclose(np.asarray(up_r), np.asarray(up_ref),
                               rtol=1e-3, atol=5e-3)


def test_rows_shards_config_validation():
    import dataclasses

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    with pytest.raises(ValueError, match="at most one"):
        RaftStereoConfig(rows_shards=2, banded_encoder=True)

    # tracing without an active mesh raises with the fix-it instruction
    cfg = RaftStereoConfig(n_gru_layers=1, hidden_dims=(32,), corr_levels=2,
                           fnet_dim=64, rows_shards=2)
    model = RAFTStereo(cfg)
    img = jnp.zeros((1, 32, 64, 3), jnp.float32)
    v = model.init(jax.random.PRNGKey(0), img, img, iters=1, test_mode=True)
    with pytest.raises(RuntimeError, match="rows_sharding"):
        model.apply(v, img, img, iters=1, test_mode=True)


def test_rows_sharded_two_axis_mesh(rng):
    """Rows sharded over 'data' while a 'corr' axis coexists on the same
    mesh — the precondition for composing with the W2-sharded volume."""
    from raft_stereo_tpu.parallel.mesh import make_mesh

    trunk = _Trunk("instance", downsample=2, dtype=jnp.float32)
    x = jnp.asarray(rng.uniform(-1, 1, (1, 64, 32, 3)), jnp.float32)
    v = trunk.init(jax.random.PRNGKey(0), x)
    want = np.asarray(trunk.apply(v, x))
    mesh = make_mesh(n_data=4, n_corr=2)  # 8 devices, two axes
    got = np.asarray(rows_sharded_trunk_apply(
        v["params"], v.get("batch_stats", {}), x, "instance", jnp.float32,
        mesh=mesh, axis="data", halo=16))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
