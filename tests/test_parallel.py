"""W2-sharded correlation (parallel/corr_sharded.py) vs the unsharded reg
backend, on the 8-virtual-CPU-device mesh (conftest).

The sharded path must agree with ``reg`` to numerical precision — values AND
gradients — including awkward W2 (padding + floor-pooling masking) and
fractional/out-of-range lookup coordinates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RaftStereoConfig
from raft_stereo_tpu.models.corr import make_corr_fn, make_corr_fn_reg
from raft_stereo_tpu.parallel import corr_sharding, make_mesh
from raft_stereo_tpu.parallel.corr_sharded import make_corr_fn_w2_sharded


def _fmaps(rng, b, h, w1, w2, d=16):
    f1 = jnp.asarray(rng.standard_normal((b, h, w1, d)), jnp.float32)
    f2 = jnp.asarray(rng.standard_normal((b, h, w2, d)), jnp.float32)
    return f1, f2


def _coords(rng, b, h, w1, w2):
    # Cover in-range, fractional, and out-of-range positions.
    c = rng.uniform(-3.0, w2 + 3.0, (b, h, w1))
    return jnp.asarray(c, jnp.float32)


@pytest.mark.slow
@pytest.mark.parametrize("n_corr", [2, 4])
@pytest.mark.parametrize("w2", [64, 52, 13])
def test_sharded_matches_reg(rng, n_corr, w2):
    from conftest import require_corr_mesh
    require_corr_mesh()
    cfg = RaftStereoConfig(corr_w2_shards=n_corr)
    mesh = make_mesh(n_data=8 // n_corr, n_corr=n_corr)
    b, h, w1 = 2, 4, 52
    f1, f2 = _fmaps(rng, b, h, w1, w2)
    coords = _coords(rng, b, h, w1, w2)

    ref = make_corr_fn_reg(cfg, f1, f2)(coords)
    with corr_sharding(mesh):
        out = make_corr_fn_w2_sharded(cfg, f1, f2, mesh)(coords)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_sharded_gradients_match_reg(rng):
    from conftest import require_corr_mesh
    require_corr_mesh()
    cfg = RaftStereoConfig(corr_w2_shards=2)
    mesh = make_mesh(n_data=4, n_corr=2)
    b, h, w1, w2 = 1, 4, 24, 40
    f1, f2 = _fmaps(rng, b, h, w1, w2, d=8)
    coords = _coords(rng, b, h, w1, w2)
    cot = jnp.asarray(rng.standard_normal(
        (b, h, w1, cfg.corr_channels)), jnp.float32)

    def loss_ref(f1, f2):
        return jnp.sum(make_corr_fn_reg(cfg, f1, f2)(coords) * cot)

    def loss_sharded(f1, f2):
        fn = make_corr_fn_w2_sharded(cfg, f1, f2, mesh)
        return jnp.sum(fn(coords) * cot)

    g_ref = jax.grad(loss_ref, argnums=(0, 1))(f1, f2)
    with corr_sharding(mesh):
        g_sh = jax.jit(jax.grad(loss_sharded, argnums=(0, 1)))(f1, f2)
    for a, b_ in zip(g_sh, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


def test_sharded_reg_fused_backend_matches_reg(rng):
    """corr_w2_shards with the (default) reg_fused backend: the sharded
    volume path must agree with the unsharded reg backend (fp32 inputs ⇒
    fp32 shard storage ⇒ exact)."""
    from conftest import require_corr_mesh
    require_corr_mesh()
    cfg = RaftStereoConfig(corr_w2_shards=2, corr_backend="reg_fused")
    mesh = make_mesh(n_data=4, n_corr=2)
    b, h, w1, w2 = 1, 4, 24, 40
    f1, f2 = _fmaps(rng, b, h, w1, w2, d=8)
    coords = _coords(rng, b, h, w1, w2)
    ref = make_corr_fn_reg(RaftStereoConfig(corr_backend="reg"), f1, f2)(coords)

    with corr_sharding(mesh):
        out = jax.jit(
            lambda c: make_corr_fn_w2_sharded(cfg, f1, f2, mesh)(c)
        )(coords)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_dispatch_requires_active_mesh(rng):
    cfg = RaftStereoConfig(corr_w2_shards=2)
    f1, f2 = _fmaps(rng, 1, 2, 8, 8)
    with pytest.raises(RuntimeError, match="corr_sharding"):
        make_corr_fn(cfg, f1, f2)


@pytest.mark.slow
def test_full_model_sharded_matches_unsharded(rng):
    """Whole-model forward with corr_w2_shards=2 ≡ the plain reg model."""
    from conftest import require_corr_mesh
    require_corr_mesh()
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    mesh = make_mesh(n_data=4, n_corr=2)
    cfg_plain = RaftStereoConfig(n_gru_layers=2, hidden_dims=(32, 32),
                                 fnet_dim=64)
    cfg_shard = RaftStereoConfig(n_gru_layers=2, hidden_dims=(32, 32),
                                 fnet_dim=64, corr_w2_shards=2)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 64, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 64, 3)), jnp.float32)

    model = RAFTStereo(cfg_plain)
    variables = model.init(jax.random.PRNGKey(0), img1, img2, iters=1,
                           test_mode=True)
    lo_ref, up_ref = model.apply(variables, img1, img2, iters=3,
                                 test_mode=True)

    model_sh = RAFTStereo(cfg_shard)
    with corr_sharding(mesh):
        lo_sh, up_sh = jax.jit(
            lambda v, a, b: model_sh.apply(v, a, b, iters=3, test_mode=True)
        )(variables, img1, img2)
    # fp summation-order differences (psum vs in-thread adds) amplify through
    # the recurrent GRU; per-lookup agreement is exact (tests above).
    np.testing.assert_allclose(np.asarray(lo_sh), np.asarray(lo_ref),
                               rtol=1e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(up_sh), np.asarray(up_ref),
                               rtol=1e-3, atol=2e-3)


# ------------------------------------------- Pallas kernel inside the shard
@pytest.fixture
def _interpret_mode():
    from raft_stereo_tpu.kernels import corr_lookup
    corr_lookup._interpret_override = True
    yield
    corr_lookup._interpret_override = None


@pytest.mark.slow
@pytest.mark.parametrize("b,n_data,n_corr", [(1, 4, 2), (4, 2, 4)])
def test_sharded_kernel_matches_reg(rng, _interpret_mode, b, n_data, n_corr):
    """reg_fused + corr_w2_shards engages the Pallas kernel per shard
    (full-manual shard_map); values must match unsharded reg exactly, in
    both the replicated-batch and split-batch spec branches."""
    from conftest import require_corr_mesh
    require_corr_mesh()
    cfg = RaftStereoConfig(corr_w2_shards=n_corr, corr_backend="reg_fused")
    mesh = make_mesh(n_data=n_data, n_corr=n_corr)
    h, w1, w2 = 4, 24, 40
    f1, f2 = _fmaps(rng, b, h, w1, w2, d=8)
    coords = _coords(rng, b, h, w1, w2)
    ref = make_corr_fn_reg(RaftStereoConfig(corr_backend="reg"),
                           f1, f2)(coords)

    with corr_sharding(mesh):
        out = jax.jit(
            lambda c: make_corr_fn_w2_sharded(cfg, f1, f2, mesh)(c)
        )(coords)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_sharded_kernel_gradients_match_reg(rng, _interpret_mode):
    """Feature gradients THROUGH the per-shard Pallas kernel (custom VJP
    inside a full-manual shard_map) match the unsharded reg backend."""
    from conftest import require_corr_mesh
    require_corr_mesh()
    cfg = RaftStereoConfig(corr_w2_shards=2, corr_backend="reg_fused")
    mesh = make_mesh(n_data=4, n_corr=2)
    b, h, w1, w2 = 1, 4, 24, 40
    f1, f2 = _fmaps(rng, b, h, w1, w2, d=8)
    coords = _coords(rng, b, h, w1, w2)
    cot = jnp.asarray(rng.standard_normal(
        (b, h, w1, cfg.corr_channels)), jnp.float32)

    def loss_ref(f1, f2):
        return jnp.sum(make_corr_fn_reg(
            RaftStereoConfig(corr_backend="reg"), f1, f2)(coords) * cot)

    def loss_sharded(f1, f2):
        fn = make_corr_fn_w2_sharded(cfg, f1, f2, mesh)
        return jnp.sum(fn(coords) * cot)

    g_ref = jax.grad(loss_ref, argnums=(0, 1))(f1, f2)
    with corr_sharding(mesh):
        g_sh = jax.jit(jax.grad(loss_sharded, argnums=(0, 1)))(f1, f2)
    for a, b_ in zip(g_sh, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_sharded_fullres_structure(rng, _interpret_mode):
    """Full-resolution W2 STRUCTURE (Middlebury-F at 1/4 res has W2=496)
    through the sharded volume + Pallas kernel on the virtual mesh — H kept
    tiny so the CPU interpreter stays fast; the W2 math (padding quantum,
    level widths 496/248/124/62, shard offsets) is the full-res case."""
    from conftest import require_corr_mesh
    require_corr_mesh()
    cfg = RaftStereoConfig(corr_w2_shards=4, corr_backend="reg_fused")
    mesh = make_mesh(n_data=2, n_corr=4)
    b, h, w1, w2 = 1, 2, 496, 496
    f1, f2 = _fmaps(rng, b, h, w1, w2, d=16)
    coords = _coords(rng, b, h, w1, w2)
    ref = make_corr_fn_reg(RaftStereoConfig(corr_backend="reg"),
                           f1, f2)(coords)
    with corr_sharding(mesh):
        out = jax.jit(
            lambda c: make_corr_fn_w2_sharded(cfg, f1, f2, mesh)(c)
        )(coords)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
