"""Round-24 quality observability: per-request confidence maps, the
confidence-OFF bitwise pin, the confidence-gated cascade tier, and the
online quality trackers.

The contracts pinned here (ISSUE round 24):

* OFF pin — ``return_confidence`` defaulted/False lowers EVERY program
  family (base, early-exit, state, warm, warm+hidden, ctx save/reuse)
  to byte-identical StableHLO: the flag off is unobservable, down to
  the compiled program.  The engine's cost and persist keys gain the
  ``,conf`` coordinate ONLY when ``ServeConfig.confidence`` is on.
* signal semantics — confidence is a convergence statement: a flat
  textureless pair (updates stall instantly) is confident, a
  high-frequency noise pair (correlation never locks) is doubtful, and
  turning the map on never changes the flow bytes.
* cascade — ``tier="auto"`` drafts cheap, escalates only the doubtful
  answer, and stamps the provenance (draft tier + draft confidence) on
  the result; without the cascade configured "auto" is a typed error.
* trackers — the PSI drift watchdog fires ONCE per excursion (latched,
  re-arms on recovery), the quality tracker feeds the SLO/registry,
  brownout spares low-confidence requests, and a sustained shadow
  confidence drop demotes a canary under the same hysteresis as EPE.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

TINY = dict(hidden_dims=(32, 32, 32), fnet_dim=64, corr_backend="reg")
ITERS = 4
HW = (48, 64)


@pytest.fixture(scope="module")
def tiny_model():
    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig(**TINY)
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    return cfg, variables


def _pair(seed=3, textured=True):
    if not textured:   # zero texture: updates stall, confidence ~ 1
        left = np.full(HW + (3,), 127, np.uint8)
        return left, left.copy()
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 255, HW + (3,), dtype=np.uint8)
    return left, np.roll(left, -3, axis=1)


def _as_batch(*imgs):
    return jnp.asarray(np.stack(imgs).astype(np.float32))


# ------------------------------------------------------------- model level
def test_model_confidence_tuple_and_flow_bitwise_unchanged(tiny_model):
    """``return_confidence=True`` appends one (conf_low, conf_up) element
    and changes NOTHING else: disparity and flow stay bitwise-equal to
    the plain call."""
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.config import RaftStereoConfig

    cfg, variables = tiny_model
    model = RAFTStereo(RaftStereoConfig(**TINY))
    i1, i2 = map(_as_batch, _pair())
    d0, f0 = model.apply(variables, i1, i2, iters=ITERS, test_mode=True)
    d1, f1, conf = model.apply(variables, i1, i2, iters=ITERS,
                               test_mode=True, return_confidence=True)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(f0), np.asarray(f1))
    conf_low, conf_up = conf
    assert conf_up.shape == (1,) + HW
    c = np.asarray(conf_up)
    assert np.all(c > 0.0) and np.all(c <= 1.0)
    assert conf_low.ndim == 3 and conf_low.shape[0] == 1


def test_model_confidence_is_test_mode_only(tiny_model):
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.config import RaftStereoConfig

    cfg, variables = tiny_model
    model = RAFTStereo(RaftStereoConfig(**TINY))
    i1, i2 = map(_as_batch, _pair())
    with pytest.raises(ValueError, match="test-mode"):
        model.apply(variables, i1, i2, iters=2, test_mode=False,
                    return_confidence=True)


# -------------------------------------------------- the OFF program pin
def _families(cfg):
    """Every make_forward program family and its extra lowering avals."""
    f = cfg.downsample_factor
    low = jax.ShapeDtypeStruct((1, HW[0] // f, HW[1] // f), jnp.float32)
    return {
        "base": ({}, ()),
        "state": ({"return_state": True}, ()),
        "warm": ({"warm_start": True}, (low,)),
        "warm_hidden": ({"warm_start": True, "return_hidden": True},
                        (low,)),
        "ctx_save": ({"return_state": True, "ctx": "save"}, ()),
    }


@pytest.mark.parametrize("family", ["base", "state", "warm",
                                    "warm_hidden", "ctx_save"])
def test_conf_off_program_byte_identical_per_family(tiny_model, family):
    """The pin: with the flag off (default OR explicit False) every
    family lowers to byte-identical StableHLO — and ON is a genuinely
    different program (the extra confidence output)."""
    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.config import RaftStereoConfig

    cfg, variables = tiny_model
    model = RAFTStereo(RaftStereoConfig(**TINY))
    kwargs, extra = _families(cfg)[family]
    img = jax.ShapeDtypeStruct((1,) + HW + (3,), jnp.float32)

    def lower_text(**kw):
        fwd = make_forward(model, iters=ITERS, donate_images=False,
                           **kwargs, **kw)
        return fwd.lower(variables, img, img, *extra).as_text()

    t_default = lower_text()
    t_off = lower_text(return_confidence=False)
    t_on = lower_text(return_confidence=True)
    assert t_default == t_off, (
        f"{family}: return_confidence=False must lower the DEFAULT "
        f"program byte-for-byte")
    assert t_on != t_off, (
        f"{family}: the confidence variant must be a distinct program")


def test_conf_off_program_byte_identical_ctx_reuse(tiny_model):
    """ctx='reuse' takes the context bundle as a traced INPUT; its avals
    come from eval_shape of the save program (no compile)."""
    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.config import RaftStereoConfig

    cfg, variables = tiny_model
    model = RAFTStereo(RaftStereoConfig(**TINY))
    img = jax.ShapeDtypeStruct((1,) + HW + (3,), jnp.float32)
    save = make_forward(model, iters=ITERS, donate_images=False,
                        return_state=True, ctx="save")
    bundle_avals = jax.eval_shape(save, variables, img, img)[-1]

    def lower_text(**kw):
        fwd = make_forward(model, iters=ITERS, donate_images=False,
                           return_state=True, ctx="reuse", **kw)
        return fwd.lower(variables, img, img, bundle_avals).as_text()

    assert lower_text() == lower_text(return_confidence=False)
    assert lower_text(return_confidence=True) != lower_text()


def test_conf_off_early_exit_program_byte_identical(tiny_model):
    """The while-loop (early-exit) family holds the same pin."""
    import dataclasses

    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.config import RaftStereoConfig

    cfg, variables = tiny_model
    ee_cfg = dataclasses.replace(RaftStereoConfig(**TINY),
                                 exit_threshold_px=0.05,
                                 exit_min_iters=1)
    model = RAFTStereo(ee_cfg)
    img = jax.ShapeDtypeStruct((1,) + HW + (3,), jnp.float32)

    def lower_text(**kw):
        fwd = make_forward(model, iters=ITERS, donate_images=False, **kw)
        return fwd.lower(variables, img, img).as_text()

    assert lower_text() == lower_text(return_confidence=False)
    assert lower_text(return_confidence=True) != lower_text()


# ------------------------------------------------------- signal semantics
def test_flat_ranks_above_noise(tiny_model):
    """Confidence is a convergence statement: the textureless pair's
    updates stall sooner than high-frequency noise's, so it RANKS more
    confident — at any depth.  (Absolute calibration needs trained
    weights; tools/confidence_report.py and scripts/quality_smoke.py
    measure it.  Random-init weights keep every update large, so both
    values are small — the ordering is the invariant.)"""
    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.config import RaftStereoConfig

    cfg, variables = tiny_model
    model = RAFTStereo(RaftStereoConfig(**TINY))
    fwd = make_forward(model, iters=2, donate_images=False,
                       return_confidence=True)

    def conf_mean(pair):
        l, r = pair
        out = fwd(variables, _as_batch(l), _as_batch(r))
        _conf_low, conf_up = out[-1]
        c = np.asarray(conf_up)
        assert np.all(c > 0.0) and np.all(c <= 1.0)
        return float(c.mean())

    c_flat = conf_mean(_pair(textured=False))
    c_noise = conf_mean(_pair(textured=True))
    assert c_flat > c_noise, (c_flat, c_noise)


# ------------------------------------------------------------ engine level
def test_engine_confidence_off_result_and_keys_unchanged(tiny_model):
    """``confidence=False`` keeps the round-23 surface byte-for-byte:
    no confidence fields on the result, no ``,conf`` coordinate in the
    cost key, the identical disk key — and ``tier="auto"`` is a typed
    refusal without the cascade."""
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    l, r = _pair()
    with StereoService(cfg, variables,
                       ServeConfig(max_batch=1, batch_sizes=(1,),
                                   iters=ITERS)) as svc:
        res = svc.infer(l, r, timeout=300)
        assert res.confidence is None and res.confidence_mean is None
        assert res.escalated is False and res.draft_tier is None
        key = svc._cost_key((64, 64), 1)
        assert "conf" not in key
        assert svc.quality is None and svc.quality_status() is None
        with pytest.raises(ValueError, match="cascade"):
            svc.infer(l, r, tier="auto", timeout=10)


def test_engine_cascade_escalates_doubtful_spares_easy(tiny_model):
    """tier="auto": noise drafts cheap, comes back doubtful, escalates
    (provenance stamped); flat resolves at the draft.  Confidence ON
    never changes the flow bytes, and the key space gains ``,conf``.

    The gate threshold is pre-measured (scripts/quality_smoke.py's
    protocol): random-init weights keep absolute confidence low
    everywhere, so the test splits the two probes' measured draft-depth
    confidences at the midpoint instead of assuming a calibrated 0.5."""
    from raft_stereo_tpu.eval.runner import make_forward
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.serving import ServeConfig, StereoService

    cfg, variables = tiny_model
    # 64x64 inputs: the dispatch bucket exactly, so the probe and the
    # engine run the same pixels (no padder in between).
    rng = np.random.default_rng(3)
    noise_l = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    noise_r = np.roll(noise_l, -3, axis=1)
    flat_l = np.full((64, 64, 3), 127, np.uint8)
    flat_r = flat_l.copy()

    probe = make_forward(RAFTStereo(RaftStereoConfig(**TINY)),
                         iters=ITERS, donate_images=False,
                         return_confidence=True)

    def conf_mean(l, r):
        out = probe(variables, _as_batch(l), _as_batch(r))
        return float(np.asarray(out[-1][1]).mean())

    c_noise = conf_mean(noise_l, noise_r)
    c_flat = conf_mean(flat_l, flat_r)
    assert c_flat > c_noise, (c_flat, c_noise)
    thr = 0.5 * (c_flat + c_noise)

    sc = ServeConfig(max_batch=1, batch_sizes=(1,), iters=ITERS,
                     tiers=("draft:0.25:2", "quality"),
                     confidence=True, cascade=True,
                     cascade_draft="draft", cascade_escalate="quality",
                     cascade_threshold=thr)
    with StereoService(cfg, variables, sc) as svc:
        hard = svc.infer(noise_l, noise_r, tier="auto", timeout=300)
        assert hard.escalated is True
        assert hard.tier == "quality" and hard.draft_tier == "draft"
        assert hard.draft_confidence is not None
        assert hard.draft_confidence < thr
        assert hard.confidence.shape == (64, 64)
        assert hard.confidence.dtype == np.float32
        assert 0.0 < hard.confidence_mean <= 1.0

        easy = svc.infer(flat_l, flat_r, tier="auto", timeout=300)
        assert easy.escalated is False
        assert easy.tier == "draft" and easy.draft_tier == "draft"
        assert easy.confidence_mean > thr

        # conf ON does not move the flow: the quality tier's answer is
        # bitwise what the same tier returns on this engine directly.
        direct = svc.infer(noise_l, noise_r, tier="quality", timeout=300)
        np.testing.assert_array_equal(hard.flow, direct.flow)

        # Drafts counts draft-ALONE answers; escalated requests bump
        # only the escalation counter (engine semantics).
        assert svc._cascade_drafts.value == 1
        assert svc._cascade_escalations.value == 1
        key = svc._cost_key((64, 64), 1, tier="quality")
        assert ",conf" in key

        q = svc.quality_status()
        assert q is not None and q["cascade"]["drafts"] == 1
        assert q["cascade"]["escalated"] == 1
        assert q["good"] + q["bad"] >= 3
        text = svc.metrics.registry.render_text()
        assert "serve_confidence_bucket" in text
        assert 'dimension="quality"' in text


def test_serve_config_cascade_validation():
    from raft_stereo_tpu.serving import ServeConfig

    with pytest.raises(ValueError, match="confidence"):
        ServeConfig(cascade=True, tiers=("interactive", "quality"),
                    cascade_draft="interactive",
                    cascade_escalate="quality")
    with pytest.raises(ValueError):
        ServeConfig(confidence=True, cascade=True,
                    tiers=("interactive", "quality"),
                    cascade_draft="nope", cascade_escalate="quality")
    with pytest.raises(ValueError):
        ServeConfig(confidence=True, confidence_floor=1.5)


# --------------------------------------------------------------- trackers
def _drift(**kw):
    from raft_stereo_tpu.telemetry.quality import QualityDriftWatchdog

    class Sink:
        def __init__(self):
            self.fired = []

        def fire(self, kind, **detail):
            self.fired.append((kind, detail))
            return {"kind": kind, **detail}

    sink = Sink()
    kw.setdefault("threshold", 0.25)
    kw.setdefault("reference_size", 40)
    kw.setdefault("window", 32)
    return QualityDriftWatchdog(sink=sink, **kw), sink


def test_drift_watchdog_fires_once_latched_then_rearms():
    wd, sink = _drift()
    # Deterministic value cycles: identical healthy traffic before and
    # after the excursion, so recovery's PSI is exactly the no-drift
    # floor (a noisy random stream at these small test windows has a
    # PSI noise floor above the threshold — production uses 256/128).
    healthy_vals = (0.82, 0.85, 0.88, 0.91)
    degraded_vals = (0.18, 0.22, 0.27, 0.31)
    healthy = lambda i: healthy_vals[i % len(healthy_vals)]
    degraded = lambda i: degraded_vals[i % len(degraded_vals)]
    for i in range(40):                       # freeze the reference
        wd.observe(healthy(i))
    assert wd.status()["reference_n"] == 40
    for i in range(64):                       # the excursion
        wd.observe(degraded(i))
    assert len(sink.fired) == 1, "latched: one excursion, ONE anomaly"
    kind, detail = sink.fired[0]
    assert kind == "quality_drift"
    assert detail["psi"] >= detail["threshold"]
    assert wd.status()["tripped"] is True
    for i in range(96):                       # recovery re-arms ...
        wd.observe(healthy(i))
    assert wd.status()["tripped"] is False
    for i in range(64):                       # ... and a NEW excursion fires
        wd.observe(degraded(i))
    assert len(sink.fired) == 2


def test_quality_tracker_totals_slo_and_rolling_mean():
    from raft_stereo_tpu.telemetry.quality import QualityTracker
    from raft_stereo_tpu.telemetry.registry import MetricsRegistry
    from raft_stereo_tpu.telemetry.slo import BurnRateTracker

    reg = MetricsRegistry()
    slo = BurnRateTracker(availability=0.9, registry=reg,
                          gauge_name="serve_slo_burn_rate",
                          dimension="quality")
    qt = QualityTracker(registry=reg, floor=0.5, slo=slo, slo_every=2)
    for c in (0.9, 0.8, 0.3, 0.95):
        qt.observe("quality", None, c)
    good, bad = qt.totals()
    assert (good, bad) == (3, 1)
    assert qt.mean_confidence("quality") == pytest.approx(
        (0.9 + 0.8 + 0.3 + 0.95) / 4)
    st = qt.status()
    assert st["good"] == 3 and st["bad"] == 1
    assert "slo" in st and "drift" in st
    text = reg.render_text()
    assert "serve_confidence_bucket" in text
    assert 'serve_slo_burn_rate{' in text and 'dimension="quality"' in text
    with pytest.raises(ValueError):
        QualityTracker(floor=1.5)


def test_brownout_spares_low_confidence_requests():
    """Victim selection: under degradation a LOW-confidence request keeps
    its tier (it needs the compute); confident traffic steps down."""
    from raft_stereo_tpu.serving.metrics import ServingMetrics
    from raft_stereo_tpu.serving.resilience import BrownoutController

    bc = BrownoutController(ServingMetrics(), max_queue=8,
                            ladder=("interactive", "balanced", "quality"))
    bc.spare_below = 0.4
    bc.set_floor(1)
    assert bc.degrade("quality", confidence=0.9) == "balanced"
    assert bc.degrade("quality", confidence=None) == "balanced"
    assert bc.degrade("quality", confidence=0.3) == "quality"
    bc.spare_below = 0.0        # telemetry off: round-13 behavior
    assert bc.degrade("quality", confidence=0.3) == "balanced"


def test_rollout_shadow_confidence_drop_demotes():
    """A canary that answers systematically LESS confident than the
    primary demotes under the same dwell hysteresis as shadow EPE."""
    from raft_stereo_tpu.serving.fleet.rollout import (RolloutConfig,
                                                       RolloutPolicy)

    clock = {"t": 0.0}
    policy = RolloutPolicy(
        RolloutConfig(min_samples=4, confidence_threshold=0.2,
                      demote_after_s=1.0),
        clock=lambda: clock["t"])
    policy.set_canary("tiny@v2", 0.3, shadow_fraction=0.5)
    for _ in range(4):
        policy.note_shadow_confidence(0.45)   # primary 0.45 more sure
    assert not policy.status()["demoted"], "dwell must gate the demotion"
    clock["t"] = 2.0
    policy.note_shadow_confidence(0.45)
    st = policy.status()
    assert st["demoted"] is True
    assert "confidence" in (st["demoted_reason"] or "")
    assert policy.assign(b"any-request") is None

    # Healthy deltas never demote: the verdict needs a sustained drop.
    policy2 = RolloutPolicy(
        RolloutConfig(min_samples=4, confidence_threshold=0.2,
                      demote_after_s=0.0),
        clock=lambda: clock["t"])
    policy2.set_canary("tiny@v2", 0.3)
    for _ in range(16):
        policy2.note_shadow_confidence(0.02)
    assert not policy2.status()["demoted"]
