"""Benchmark: full-resolution (Middlebury-F class) inference — the
long-context path.

BASELINE config 3: the reference runs Middlebury-F full resolution ONLY via
its no-volume "alt" backend (reference: README.md:121, core/corr.py:64-107)
because the reg corr volume is O(H·W·W) memory.  This measures, on one chip,
for the accuracy architecture (n_downsample=2, fp32, 32 iters):

* XLA-compiled peak HBM (``compiled.memory_analysis()`` — this runtime does
  not expose live device memory stats) for the fused no-volume ``alt``
  backend vs the volume-based ``reg_fused`` backend;
* FPS via the chained-differencing protocol (see bench.py), when the
  program fits at all.

Sizes: 1088x1984 (mid-size MiddEval3-F frames, /32-aligned) and 1984x2880
(Jadeplant-class, the largest trainingF frames).  Prints one JSON line per
(backend, size) with peak HBM and FPS; RESOURCE_EXHAUSTED is reported as
``"oom": true`` — that outcome IS the measurement for the volume path.
"""

from __future__ import annotations

import functools
import json

import argparse

import jax
import jax.numpy as jnp
import numpy as np

SIZES = ((1088, 1984), (1984, 2880))
BACKENDS = ("alt", "reg_fused")
ITERS = 32
K_LO, K_HI = 1, 3
REPEATS = 3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--banded", action="store_true",
                    help="banded encoder (models/banded.py): several-fold "
                         "lower peak HBM, ~20%% slower at full res")
    ap.add_argument("--xl_mesh", default=None,
                    help="also measure the mesh-SHARDED forward (e.g. "
                         "'rows=4'): peak HBM becomes per-device and the "
                         "ROWSGRU memory wall drops ~1/N — the raw-"
                         "forward twin of the serving xl tier "
                         "(bench_serve.py --xl measures the engine "
                         "path).  Needs rows*corr local devices")
    args = ap.parse_args()

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.profiling import chained_seconds_per_call
    from raft_stereo_tpu.telemetry.events import bench_record

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 10)

    # Shared versioned run header (telemetry/events.py); the per-(backend,
    # size) lines below are rows under it.
    print(json.dumps(bench_record(
        {"metric": "fullres_inference_run", "banded": args.banded,
         "iters": ITERS, "sizes": [f"{h}x{w}" for h, w in SIZES]})))

    import contextlib

    # Mesh-sharded variant (--xl_mesh): trace the same chained forward
    # with rows/corr sharding active — every compile below then reports
    # PER-DEVICE memory_analysis, directly comparable to the solo rows.
    mesh_ctx = contextlib.nullcontext
    mesh_kw = {}
    if args.xl_mesh:
        from raft_stereo_tpu.parallel.mesh import (ROWS_AXIS, make_mesh,
                                                   parse_mesh_spec)
        from raft_stereo_tpu.parallel.rows_sharded import rows_sharding
        spec = parse_mesh_spec(args.xl_mesh)
        mesh = make_mesh(n_data=1, n_corr=spec["corr"],
                         n_rows=spec["rows"],
                         devices=jax.devices()[:spec["rows"]
                                               * spec["corr"]])
        mesh_kw = {"rows_shards": spec["rows"],
                   "corr_w2_shards": spec["corr"],
                   "rows_gru": spec["rows"] > 1 and spec["corr"] == 1}
        if spec["rows"] > 1:
            mesh_ctx = lambda: rows_sharding(mesh, ROWS_AXIS)  # noqa: E731
        if spec["corr"] > 1:
            from raft_stereo_tpu.parallel.corr_sharded import corr_sharding
            prev_ctx = mesh_ctx

            def mesh_ctx():
                stack = contextlib.ExitStack()
                stack.enter_context(prev_ctx())
                stack.enter_context(corr_sharding(mesh))
                return stack

    rng = np.random.default_rng(0)
    results = []
    variables = None
    for backend in BACKENDS:
        try:
            cfg = RaftStereoConfig(corr_backend=backend,
                                   banded_encoder=args.banded, **mesh_kw)
        except ValueError as e:   # e.g. corr sharding x volume-free 'alt'
            print(json.dumps({"metric": "fullres_inference",
                              "backend": backend,
                              "xl_mesh": args.xl_mesh,
                              "skipped": str(e)[:160]}))
            continue
        model = RAFTStereo(cfg)
        if variables is None:
            img_s = jnp.zeros((1, 64, 96, 3), jnp.float32)
            variables = jax.jit(
                lambda r: model.init(r, img_s, img_s, iters=1, test_mode=True)
            )(jax.random.PRNGKey(0))
        for h, w in SIZES:
            img1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
            img2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)

            @functools.partial(jax.jit, static_argnums=(3,))
            def chain(variables, image1, image2, k):
                def body(i, acc):
                    _, up = model.apply(variables, image1 + i * 1e-6, image2,
                                        iters=ITERS, test_mode=True)
                    return acc + jnp.mean(up)
                return jax.lax.fori_loop(0, k, body, jnp.float32(0))

            rec = {"metric": "fullres_inference", "backend": backend,
                   "size": f"{h}x{w}", "iters": ITERS,
                   "banded_encoder": args.banded}
            if args.xl_mesh:
                rec["xl_mesh"] = args.xl_mesh
                rec["hbm_is_per_device"] = True
            try:
                with mesh_ctx():
                    compiled = chain.lower(variables, img1, img2,
                                           1).compile()
                ma = compiled.memory_analysis()
                # peak_memory_in_bytes is TPU-backend; CPU builds of
                # some jax versions expose only the size fields — fall
                # back to their sum so the per-device comparison stays
                # measurable everywhere.
                peak = getattr(ma, "peak_memory_in_bytes", None)
                if peak is None:
                    peak = (ma.temp_size_in_bytes
                            + ma.argument_size_in_bytes
                            + ma.output_size_in_bytes)
                    rec["hbm_is_live_sum"] = True
                rec["peak_hbm_gib"] = round(peak / 2 ** 30, 3)
                rec["temp_gib"] = round(ma.temp_size_in_bytes / 2 ** 30, 3)

                def make_chain(k):
                    if k == 1:  # reuse the executable compiled above
                        return lambda: float(compiled(variables, img1, img2))

                    def run_k():
                        with mesh_ctx():   # k>1 traces a fresh program
                            return float(chain(variables, img1, img2, k))
                    return run_k

                per_image = chained_seconds_per_call(
                    make_chain, k_lo=K_LO, k_hi=K_HI, repeats=REPEATS)
                rec["value"] = round(1.0 / per_image, 3)
                rec["unit"] = "frames/s"
                rec["oom"] = False
            except Exception as e:  # noqa: BLE001 - OOM is a result here
                msg = str(e)
                rec["oom"] = ("RESOURCE_EXHAUSTED" in msg
                              or "Out of memory" in msg
                              or "exceeds the limit" in msg)
                rec["error"] = msg.splitlines()[0][:200]
            print(json.dumps(rec))
            results.append(rec)
    return results


if __name__ == "__main__":
    main()
