"""Benchmark: full-resolution (Middlebury-F class) inference — the
long-context path.

BASELINE config 3: the reference runs Middlebury-F full resolution ONLY via
its no-volume "alt" backend (reference: README.md:121, core/corr.py:64-107)
because the reg corr volume is O(H·W·W) memory.  This measures, on one chip,
for the accuracy architecture (n_downsample=2, fp32, 32 iters):

* XLA-compiled peak HBM (``compiled.memory_analysis()`` — this runtime does
  not expose live device memory stats) for the fused no-volume ``alt``
  backend vs the volume-based ``reg_fused`` backend;
* FPS via the chained-differencing protocol (see bench.py), when the
  program fits at all.

Sizes: 1088x1984 (mid-size MiddEval3-F frames, /32-aligned) and 1984x2880
(Jadeplant-class, the largest trainingF frames).  Prints one JSON line per
(backend, size) with peak HBM and FPS; RESOURCE_EXHAUSTED is reported as
``"oom": true`` — that outcome IS the measurement for the volume path.
"""

from __future__ import annotations

import functools
import json

import argparse

import jax
import jax.numpy as jnp
import numpy as np

SIZES = ((1088, 1984), (1984, 2880))
BACKENDS = ("alt", "reg_fused")
ITERS = 32
K_LO, K_HI = 1, 3
REPEATS = 3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--banded", action="store_true",
                    help="banded encoder (models/banded.py): several-fold "
                         "lower peak HBM, ~20%% slower at full res")
    args = ap.parse_args()

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.profiling import chained_seconds_per_call
    from raft_stereo_tpu.telemetry.events import bench_record

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 10)

    # Shared versioned run header (telemetry/events.py); the per-(backend,
    # size) lines below are rows under it.
    print(json.dumps(bench_record(
        {"metric": "fullres_inference_run", "banded": args.banded,
         "iters": ITERS, "sizes": [f"{h}x{w}" for h, w in SIZES]})))

    rng = np.random.default_rng(0)
    results = []
    variables = None
    for backend in BACKENDS:
        cfg = RaftStereoConfig(corr_backend=backend,
                               banded_encoder=args.banded)
        model = RAFTStereo(cfg)
        if variables is None:
            img_s = jnp.zeros((1, 64, 96, 3), jnp.float32)
            variables = jax.jit(
                lambda r: model.init(r, img_s, img_s, iters=1, test_mode=True)
            )(jax.random.PRNGKey(0))
        for h, w in SIZES:
            img1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
            img2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)

            @functools.partial(jax.jit, static_argnums=(3,))
            def chain(variables, image1, image2, k):
                def body(i, acc):
                    _, up = model.apply(variables, image1 + i * 1e-6, image2,
                                        iters=ITERS, test_mode=True)
                    return acc + jnp.mean(up)
                return jax.lax.fori_loop(0, k, body, jnp.float32(0))

            rec = {"metric": "fullres_inference", "backend": backend,
                   "size": f"{h}x{w}", "iters": ITERS,
                   "banded_encoder": args.banded}
            try:
                compiled = chain.lower(variables, img1, img2, 1).compile()
                ma = compiled.memory_analysis()
                rec["peak_hbm_gib"] = round(
                    ma.peak_memory_in_bytes / 2 ** 30, 3)
                rec["temp_gib"] = round(ma.temp_size_in_bytes / 2 ** 30, 3)

                def make_chain(k):
                    if k == 1:  # reuse the executable compiled above
                        return lambda: float(compiled(variables, img1, img2))
                    return lambda: float(chain(variables, img1, img2, k))

                per_image = chained_seconds_per_call(
                    make_chain, k_lo=K_LO, k_hi=K_HI, repeats=REPEATS)
                rec["value"] = round(1.0 / per_image, 3)
                rec["unit"] = "frames/s"
                rec["oom"] = False
            except Exception as e:  # noqa: BLE001 - OOM is a result here
                msg = str(e)
                rec["oom"] = ("RESOURCE_EXHAUSTED" in msg
                              or "Out of memory" in msg
                              or "exceeds the limit" in msg)
                rec["error"] = msg.splitlines()[0][:200]
            print(json.dumps(rec))
            results.append(rec)
    return results


if __name__ == "__main__":
    main()
