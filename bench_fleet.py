"""Fleet router load harness (ROADMAP 3d): open-loop session traffic
up to 10k concurrent streaming sessions against a REAL ``raft-route``
subprocess.

Two legs:

* **Stub sweep** (the scale leg) — N in-process stub replicas answer the
  replica protocol with microsecond handlers, so every measured
  millisecond is the ROUTER: consistent-hash pick, health bookkeeping,
  forward proxy, response relay.  The sweep steps the concurrent-session
  count (default 100 → 10 000); each point offers OPEN-LOOP traffic (a
  pre-drawn Poisson arrival schedule, independent of service progress —
  a closed loop self-throttles exactly when the router is slow and hides
  queueing collapse) and records client p50/p99/p99.9, the router
  process's CPU seconds and peak RSS (/proc), and the router's own
  ledger/session bookkeeping growth.  The largest point then SIGKILLs
  one stub mid-traffic and measures the typed-410 wave and lost-ledger
  growth that failover costs.
* **Federation overhead** — the same mid-size point twice: background
  metrics federation effectively OFF (poll interval longer than the
  run) vs ON at an aggressive 1s cadence, comparing p99 and router CPU.
  The invariant under test: scraping N replicas must cost the poller,
  never the request path.
* **Real-engine leg** — a tiny real ``StereoService`` replica behind the
  same router subprocess at small N, so the record also carries an
  end-to-end routed-inference latency with actual model execution.

Prints one JSON line (bench.py contract) and writes BENCH_FLEET_r23.json
(override with --out; the CI smoke runs a seconds-scale --quick variant
to BENCH_FLEET_ci.json).

Run from the repo root::

    JAX_PLATFORMS=cpu python bench_fleet.py              # full sweep
    python bench_fleet.py --sessions 100,1000 --duration_s 5
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)

OUT = "BENCH_FLEET_r23.json"
_PAGE = os.sysconf("SC_PAGE_SIZE")
_HZ = os.sysconf("SC_CLK_TCK")


# ---------------------------------------------------------------- helpers
def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), resp.read()


def _proc_cpu_s(pid: int) -> float:
    """utime+stime of one process in seconds (/proc/<pid>/stat)."""
    with open(f"/proc/{pid}/stat") as f:
        fields = f.read().rsplit(")", 1)[1].split()
    return (int(fields[11]) + int(fields[12])) / _HZ


def _proc_rss_mb(pid: int) -> float:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def _metric(text: str, name: str) -> float:
    import re

    hits = re.findall(rf"^{name}(?:{{[^}}]*}})?\s+([0-9.eE+-]+)$",
                      text, re.M)
    return sum(float(h) for h in hits)


# ----------------------------------------------------------- stub replica
class StubReplica:
    """Protocol-complete, microsecond-cheap replica: the router is the
    only thing being measured.  Same surface the fleet tests script —
    healthz/readyz/metrics/spans plus the stream + stateless routes."""

    def __init__(self, name: str):
        self.name = name
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, body, ctype="application/json",
                      extra=()):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, json.dumps({
                        "status": "ok", "ready": True, "queue_depth": 0,
                        "queue_limit": 64, "inflight": 0,
                        "brownout_level": 0, "xl": None,
                        "sessions_active": 0}).encode())
                elif self.path == "/readyz":
                    self._send(200, b'{"ready": true}')
                elif self.path.split("?")[0] == "/metrics":
                    self._send(
                        200,
                        (f"# HELP stub_up Stub liveness.\n"
                         f"# TYPE stub_up gauge\n"
                         f'stub_up{{stub="{outer.name}"}} 1\n').encode(),
                        ctype="text/plain; version=0.0.4")
                else:
                    self._send(404, b'{"error": "no route"}')

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                path = self.path.split("?")[0]
                if path.startswith("/v1/stream/"):
                    sid = path[len("/v1/stream/"):]
                    self._send(200, b"frame:" + body,
                               ctype="application/x-npy",
                               extra=[("X-Session-Id", sid),
                                      ("X-Warm", "1")])
                elif path == "/v1/disparity":
                    self._send(200, b"disp:" + body,
                               ctype="application/x-npy",
                               extra=[("X-Batch-Size", "1")])
                else:
                    self._send(404, b'{"error": "no route"}')

        srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        srv.daemon_threads = True
        srv.request_queue_size = 512
        self.server = srv
        self.url = f"http://127.0.0.1:{srv.server_address[1]}"
        self._thread = threading.Thread(target=srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def kill(self):
        self.server.shutdown()
        self.server.server_close()


class RouterProc:
    """The measured ``raft-route`` subprocess."""

    def __init__(self, replicas, workdir, federation_poll_s=5.0,
                 trace_sample_rate=0.0, http_workers=128):
        self.port = _free_port()
        self.url = f"http://127.0.0.1:{self.port}"
        self.log_path = os.path.join(workdir, f"router-{self.port}.log")
        self._log = open(self.log_path, "wb")
        argv = [sys.executable, "-m", "raft_stereo_tpu.cli.route",
                "--host", "127.0.0.1", "--port", str(self.port),
                "--health_poll_s", "0.5", "--fail_after", "2",
                "--request_timeout_s", "60", "--no-fleet_brownout",
                "--federation_poll_s", str(federation_poll_s),
                "--trace_sample_rate", str(trace_sample_rate),
                "--http_workers", str(http_workers)]
        for name, url in replicas.items():
            argv += ["--replica", f"{name}={url}"]
        self.proc = subprocess.Popen(
            argv, cwd=_REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            stdout=self._log, stderr=self._log)

    def wait_ready(self, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"router exited rc={self.proc.returncode}")
            try:
                if _get(f"{self.url}/readyz", timeout=5)[0] == 200:
                    return
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.1)
        raise RuntimeError("router never became ready")

    def cpu_s(self) -> float:
        return _proc_cpu_s(self.proc.pid)

    def rss_mb(self) -> float:
        return _proc_rss_mb(self.proc.pid)

    def metrics(self) -> str:
        return _get(f"{self.url}/metrics", timeout=10)[2].decode()

    def cleanup(self):
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=30)
        self._log.close()


# ------------------------------------------------------------- load phase
def open_loop_sessions(router_url: str, n_sessions: int, rate_hz: float,
                       duration_s: float, workers: int, seed: int = 7):
    """Offer Poisson traffic at ``rate_hz`` total across ``n_sessions``
    distinct streaming sessions for ``duration_s``.  The arrival
    schedule is drawn UP FRONT; workers send each frame at its scheduled
    offset regardless of how previous frames fared (open loop).  Returns
    (latencies_s sorted, status counts, offered, achieved_rate)."""
    rng = np.random.default_rng(seed)
    n_arrivals = max(1, int(rate_hz * duration_s))
    offsets = np.cumsum(rng.exponential(1.0 / rate_hz, n_arrivals))
    offsets = offsets[offsets < duration_s]
    sids = [f"s{seed}-{i}" for i in range(n_sessions)]
    # Round-robin assignment keeps every session active through the
    # window; the ones due first are spread over all replicas.
    latencies = []
    statuses = {}
    lock = threading.Lock()
    idx = [0]
    t0 = time.perf_counter()

    def _worker():
        while True:
            with lock:
                i = idx[0]
                if i >= len(offsets):
                    return
                idx[0] += 1
            due = t0 + offsets[i]
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            sid = sids[i % n_sessions]
            req = urllib.request.Request(
                f"{router_url}/v1/stream/{sid}", data=b"frame",
                method="POST",
                headers={"Content-Type": "application/x-npz",
                         "Connection": "close"})
            t_send = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    resp.read()
                    code = resp.status
            except urllib.error.HTTPError as e:
                e.read()
                code = e.code
            except (urllib.error.URLError, OSError):
                code = -1
            lat = time.perf_counter() - t_send
            with lock:
                latencies.append(lat)
                statuses[code] = statuses.get(code, 0) + 1

    threads = [threading.Thread(target=_worker, daemon=True)
               for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 120)
    wall = time.perf_counter() - t0
    latencies.sort()
    return latencies, statuses, len(offsets), len(latencies) / wall


def _point_record(name, n_sessions, rate_hz, lat, statuses, offered,
                  achieved, cpu_d, rss_peak, router_metrics):
    ok = statuses.get(200, 0)
    total = sum(statuses.values())
    return {
        "leg": name,
        "sessions": n_sessions,
        "offered_rate_hz": round(rate_hz, 1),
        "offered": offered,
        "answered": total,
        "ok_200": ok,
        "statuses": {str(k): v for k, v in sorted(statuses.items())},
        "achieved_rate_hz": round(achieved, 1),
        "p50_ms": round(_pct(lat, 0.50) * 1e3, 2) if lat else None,
        "p99_ms": round(_pct(lat, 0.99) * 1e3, 2) if lat else None,
        "p999_ms": round(_pct(lat, 0.999) * 1e3, 2) if lat else None,
        "max_ms": round(lat[-1] * 1e3, 2) if lat else None,
        "router_cpu_s": round(cpu_d, 2),
        "router_rss_peak_mb": round(rss_peak, 1),
        "router_sessions_routed": int(_metric(
            router_metrics, "fleet_requests_routed_total")),
        "lost_ledger_size": int(_metric(
            router_metrics, "fleet_lost_ledger_size")),
    }


def stub_sweep(points, duration_s, session_hz, max_rate, workers,
               n_replicas, workdir, federation_poll_s=5.0):
    """The scale leg: one router process, fresh stub fleet per point."""
    out = []
    for n_sessions in points:
        stubs = [StubReplica(f"b{i}") for i in range(n_replicas)]
        router = RouterProc({s.name: s.url for s in stubs}, workdir,
                            federation_poll_s=federation_poll_s)
        try:
            router.wait_ready()
            rate = min(max_rate, n_sessions * session_hz)
            cpu0, rss0 = router.cpu_s(), router.rss_mb()
            lat, statuses, offered, achieved = open_loop_sessions(
                router.url, n_sessions, rate, duration_s, workers)
            cpu1, rss1 = router.cpu_s(), router.rss_mb()
            rec = _point_record("stub", n_sessions, rate, lat, statuses,
                                offered, achieved, cpu1 - cpu0,
                                max(rss0, rss1), router.metrics())
            out.append(rec)
            print(f"[bench_fleet] {n_sessions} sessions @ "
                  f"{rate:.0f}/s: p50 {rec['p50_ms']}ms p99 "
                  f"{rec['p99_ms']}ms p99.9 {rec['p999_ms']}ms, "
                  f"router cpu {rec['router_cpu_s']}s rss "
                  f"{rec['router_rss_peak_mb']}MB", flush=True)
        finally:
            router.cleanup()
            for s in stubs:
                try:
                    s.kill()
                except Exception:
                    pass
    return out


def failover_leg(n_sessions, duration_s, session_hz, max_rate, workers,
                 n_replicas, workdir):
    """Kill one stub mid-traffic at the largest point: measures the
    typed-410 wave (sticky sessions on the dead member) and the
    lost-ledger growth the failover writes."""
    stubs = [StubReplica(f"k{i}") for i in range(n_replicas)]
    router = RouterProc({s.name: s.url for s in stubs}, workdir)
    try:
        router.wait_ready()
        rate = min(max_rate, n_sessions * session_hz)
        killer = threading.Timer(duration_s / 3.0, stubs[0].kill)
        killer.start()
        cpu0 = router.cpu_s()
        lat, statuses, offered, achieved = open_loop_sessions(
            router.url, n_sessions, rate, duration_s, workers, seed=11)
        killer.cancel()
        cpu1 = router.cpu_s()
        metrics = router.metrics()
        rec = _point_record("failover", n_sessions, rate, lat, statuses,
                            offered, achieved, cpu1 - cpu0,
                            router.rss_mb(), metrics)
        rec["killed_replica"] = stubs[0].name
        rec["typed_410"] = statuses.get(410, 0)
        rec["sessions_lost_total"] = int(_metric(
            metrics, "fleet_sessions_lost_total"))
        rec["failovers_total"] = int(_metric(
            metrics, "fleet_failovers_total"))
        print(f"[bench_fleet] failover @ {n_sessions} sessions: "
              f"{rec['typed_410']} typed 410s, ledger "
              f"{rec['lost_ledger_size']}, p99 {rec['p99_ms']}ms",
              flush=True)
        return rec
    finally:
        router.cleanup()
        for s in stubs:
            try:
                s.kill()
            except Exception:
                pass


def federation_overhead_leg(n_sessions, duration_s, session_hz,
                            max_rate, workers, n_replicas, workdir):
    """Same load twice: federation poller idle vs aggressive.  The
    request path must not notice (render is cache-only)."""
    runs = {}
    for label, poll_s in (("off", 3600.0), ("on_1s", 1.0)):
        pts = stub_sweep([n_sessions], duration_s, session_hz, max_rate,
                         workers, n_replicas, workdir,
                         federation_poll_s=poll_s)
        runs[label] = pts[0]
    off, on = runs["off"], runs["on_1s"]
    overhead = {
        "sessions": n_sessions,
        "off": off, "on_1s": on,
        "p99_delta_ms": (round(on["p99_ms"] - off["p99_ms"], 2)
                         if on["p99_ms"] and off["p99_ms"] else None),
        "cpu_delta_s": round(on["router_cpu_s"] - off["router_cpu_s"],
                             2),
    }
    print(f"[bench_fleet] federation overhead: p99 delta "
          f"{overhead['p99_delta_ms']}ms, cpu delta "
          f"{overhead['cpu_delta_s']}s", flush=True)
    return overhead


def real_engine_leg(n_sessions, duration_s, workers, workdir):
    """Small-N leg with a REAL tiny engine replica behind the router:
    the record carries an actual routed-inference latency."""
    import io

    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.serving import ServeConfig, StereoService
    from raft_stereo_tpu.serving.http import StereoHTTPServer

    cfg = RaftStereoConfig(hidden_dims=(32, 32, 32), fnet_dim=64,
                           corr_backend="reg")
    model = RAFTStereo(cfg)
    dummy = jnp.zeros((1, 32, 48, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy, iters=1,
                           test_mode=True)
    rng = np.random.default_rng(3)
    left = rng.integers(0, 255, (48, 64, 3), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, left=left, right=np.roll(left, -3, axis=1))
    payload = buf.getvalue()

    svc = StereoService(cfg, variables,
                        ServeConfig(max_batch=2, batch_sizes=(1, 2),
                                    iters=1, sessions=True))
    server = StereoHTTPServer(svc, port=0).start()
    router = RouterProc({"real0": server.url}, workdir,
                        trace_sample_rate=1.0)
    try:
        router.wait_ready()
        # one warmup frame compiles the ladder outside the clock
        req = urllib.request.Request(
            f"{router.url}/v1/stream/warmup", data=payload,
            method="POST",
            headers={"Content-Type": "application/x-npz"})
        with urllib.request.urlopen(req, timeout=600) as resp:
            resp.read()
        lock = threading.Lock()
        latencies, statuses = [], {}
        traced = [0]
        deadline = time.monotonic() + duration_s
        sids = [f"real-{i}" for i in range(n_sessions)]

        def _worker(wid):
            i = wid
            while time.monotonic() < deadline:
                sid = sids[i % n_sessions]
                i += workers
                req = urllib.request.Request(
                    f"{router.url}/v1/stream/{sid}", data=payload,
                    method="POST",
                    headers={"Content-Type": "application/x-npz"})
                t0 = time.perf_counter()
                try:
                    with urllib.request.urlopen(req,
                                                timeout=120) as resp:
                        resp.read()
                        code = resp.status
                        has_trace = bool(
                            resp.headers.get("X-Trace-Id"))
                except urllib.error.HTTPError as e:
                    e.read()
                    code, has_trace = e.code, False
                except (urllib.error.URLError, OSError):
                    code, has_trace = -1, False
                lat = time.perf_counter() - t0
                with lock:
                    latencies.append(lat)
                    statuses[code] = statuses.get(code, 0) + 1
                    traced[0] += 1 if has_trace else 0

        cpu0 = router.cpu_s()
        threads = [threading.Thread(target=_worker, args=(w,),
                                    daemon=True)
                   for w in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=duration_s + 300)
        cpu_d = router.cpu_s() - cpu0
        latencies.sort()
        rec = {
            "leg": "real_engine",
            "sessions": n_sessions,
            "answered": sum(statuses.values()),
            "ok_200": statuses.get(200, 0),
            "traced_responses": traced[0],
            "p50_ms": (round(_pct(latencies, 0.50) * 1e3, 2)
                       if latencies else None),
            "p99_ms": (round(_pct(latencies, 0.99) * 1e3, 2)
                       if latencies else None),
            "router_cpu_s": round(cpu_d, 2),
        }
        print(f"[bench_fleet] real engine @ {n_sessions} sessions: "
              f"{rec['ok_200']}/{rec['answered']} ok, p50 "
              f"{rec['p50_ms']}ms p99 {rec['p99_ms']}ms, "
              f"{rec['traced_responses']} traced", flush=True)
        return rec
    finally:
        router.cleanup()
        server.shutdown()
        svc.close()


# -------------------------------------------------------------------- main
def build_parser():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sessions", default="100,1000,5000,10000",
                   help="comma list of concurrent-session sweep points")
    p.add_argument("--duration_s", type=float, default=12.0)
    p.add_argument("--session_hz", type=float, default=0.5,
                   help="offered frames/s per session before the "
                        "--max_rate cap")
    p.add_argument("--max_rate", type=float, default=1500.0,
                   help="total offered frames/s cap (the Python client "
                        "is part of the harness; past this the client "
                        "is the bottleneck, not the router)")
    p.add_argument("--workers", type=int, default=192,
                   help="client sender threads")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--real_sessions", type=int, default=8)
    p.add_argument("--real_duration_s", type=float, default=8.0)
    p.add_argument("--skip_real", action="store_true")
    p.add_argument("--skip_federation", action="store_true")
    p.add_argument("--skip_failover", action="store_true")
    p.add_argument("--quick", action="store_true",
                   help="seconds-scale CI preset (small sweep, short "
                        "windows)")
    p.add_argument("--out", default=os.path.join(_REPO, OUT))
    return p


def main(argv=None) -> int:
    import tempfile

    from raft_stereo_tpu.telemetry.events import (bench_record,
                                                  write_record)

    args = build_parser().parse_args(argv)
    if args.quick:
        args.sessions = "50,200"
        args.duration_s = min(args.duration_s, 4.0)
        args.workers = min(args.workers, 48)
        args.max_rate = min(args.max_rate, 300.0)
        args.real_duration_s = min(args.real_duration_s, 5.0)
    points = [int(x) for x in args.sessions.split(",") if x]
    workdir = tempfile.mkdtemp(prefix="raft-bench-fleet-")

    sweep = stub_sweep(points, args.duration_s, args.session_hz,
                       args.max_rate, args.workers, args.replicas,
                       workdir)
    failover = None
    if not args.skip_failover:
        failover = failover_leg(points[-1], args.duration_s,
                                args.session_hz, args.max_rate,
                                args.workers, args.replicas, workdir)
    federation = None
    if not args.skip_federation:
        mid = points[min(1, len(points) - 1)]
        federation = federation_overhead_leg(
            mid, args.duration_s, args.session_hz, args.max_rate,
            args.workers, args.replicas, workdir)
    real = None
    if not args.skip_real:
        real = real_engine_leg(args.real_sessions, args.real_duration_s,
                               min(8, args.workers), workdir)

    top = sweep[-1]
    rec = bench_record({
        "metric": "fleet_router_p99_ms_at_max_sessions",
        "value": top["p99_ms"],
        "unit": (f"client-observed p99 ms at {top['sessions']} "
                 f"concurrent sessions, {top['offered_rate_hz']}/s "
                 f"offered open-loop, {args.replicas} stub replicas, "
                 f"CPU"),
        "fleet_load": {
            "sweep": sweep,
            "failover": failover,
            "federation_overhead": federation,
            "real_engine": real,
            "config": {
                "duration_s": args.duration_s,
                "session_hz": args.session_hz,
                "max_rate": args.max_rate,
                "workers": args.workers,
                "replicas": args.replicas,
                "quick": args.quick,
            },
        },
    })
    print(json.dumps(rec))
    write_record(args.out, rec, indent=1)
    print(f"bench_fleet OK -> {args.out}")
    import shutil

    shutil.rmtree(workdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
