"""Benchmark: host input-pipeline throughput (the training-story gap VERDICT
round 2 flagged — bench_train feeds a synthetic in-memory batch, so nothing
showed the REAL loader can keep the chip busy).

Builds a synthetic SceneFlow-layout TRAIN tree (540x960 PNG pairs + PFM
disparity — the real on-disk formats, reference: core/stereo_datasets.py:
123-184) and measures:

* images/s of the full pipeline (decode -> DenseAugmentor -> batch stack)
  by worker-thread count, against the demand of the measured chip step rate
  (steps/s x batch 8 at the SceneFlow config, BENCH_TRAIN_r03.json);
* with --device: a combined run — the real ``StereoLoader`` feeding the
  jitted train step on the TPU — reporting seconds/step next to the
  synthetic-batch step time, so host-boundedness (or not) is a measurement,
  not a guess.

Prints one JSON line per measurement (bench.py contract).
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

import numpy as np

H, W = 540, 960          # SceneFlow native frame size
CROP = (320, 720)        # the reference's SceneFlow training crop
BATCH = 8


def build_tree(root: str, n_pairs: int, seed: int = 0, hw=(H, W)) -> None:
    """FlyingThings3D/frames_cleanpass/TRAIN layout with realistic content:
    smooth low-frequency images (PNG deflate cost sits between noise and
    natural images) and a smooth positive disparity field."""
    from PIL import Image

    from raft_stereo_tpu.data.frame_utils import write_pfm

    h, w = hw
    rng = np.random.default_rng(seed)
    base = np.kron(rng.uniform(0, 255, (-(-h // 20), -(-w // 20), 3)),
                   np.ones((20, 20, 1)))[:h, :w]

    for i in range(n_pairs):
        seq = os.path.join(root, "FlyingThings3D", "frames_cleanpass",
                           "TRAIN", "A", f"{i:04d}")
        dseq = os.path.join(root, "FlyingThings3D", "disparity", "TRAIN",
                            "A", f"{i:04d}", "left")
        os.makedirs(os.path.join(seq, "left"), exist_ok=True)
        os.makedirs(os.path.join(seq, "right"), exist_ok=True)
        os.makedirs(dseq, exist_ok=True)
        noise = rng.integers(0, 30, (h, w, 3))
        left = np.clip(base + noise, 0, 255).astype(np.uint8)
        right = np.clip(np.roll(base, -12, axis=1) + noise, 0,
                        255).astype(np.uint8)
        disp = (8.0 + 40.0 * rng.random((h, w))).astype(np.float32)
        Image.fromarray(left).save(os.path.join(seq, "left", "0006.png"))
        Image.fromarray(right).save(os.path.join(seq, "right", "0006.png"))
        write_pfm(os.path.join(dseq, "0006.pfm"), disp)


def make_loader(root: str, workers: int, photometric: bool = True,
                worker_type: str = "thread"):
    from raft_stereo_tpu.data.datasets import SceneFlow
    from raft_stereo_tpu.data.loader import StereoLoader

    aug = {"crop_size": CROP, "min_scale": -0.2, "max_scale": 0.4,
           "do_flip": None, "yjitter": True, "photometric": photometric}
    ds = SceneFlow(aug, root=root, dstype="frames_cleanpass")
    return StereoLoader(ds, batch_size=BATCH, num_workers=workers,
                        prefetch=2, seed=0, worker_type=worker_type)


def measure_host(root: str, workers: int, n_batches: int,
                 photometric: bool = True,
                 worker_type: str = "thread") -> float:
    loader = make_loader(root, workers, photometric, worker_type)
    it = iter(loader)
    next(it)  # warm: thread spin-up, file-cache population
    t0 = time.perf_counter()
    for _ in range(n_batches):
        next(it)
    dt = time.perf_counter() - t0
    del it
    return n_batches * BATCH / dt


def stage_breakdown(root: str) -> dict:
    """Per-stage host ms for one sample (decode, photometric, spatial) —
    the evidence for what device_photometric moves off the host."""
    import glob as _glob

    from raft_stereo_tpu.data import frame_utils
    from raft_stereo_tpu.data.augment import DenseAugmentor, _eraser

    candidates = []
    for dstype in ("frames_cleanpass", "frames_finalpass"):
        candidates += sorted(_glob.glob(os.path.join(
            root, "FlyingThings3D", dstype, "TRAIN/*/*/left/*.png")))[:1]
    if not candidates:  # e.g. a Monkaa/Driving-only root: skip, don't crash
        return {"skipped": "no FlyingThings TRAIN pair under this root"}
    left_p = candidates[0]
    right_p = left_p.replace("left", "right")
    dstype = left_p.split(os.sep + "FlyingThings3D" + os.sep)[1].split(
        os.sep)[0]
    disp_p = left_p.replace(dstype, "disparity").replace(".png", ".pfm")
    aug = DenseAugmentor(CROP, -0.2, 0.4, None, True)
    rngf = lambda: np.random.default_rng(0)  # noqa: E731

    def t(f, n=15):
        f()
        t0 = time.perf_counter()
        for _ in range(n):
            f()
        return (time.perf_counter() - t0) / n * 1e3

    img1 = frame_utils.read_image(left_p)
    img2 = frame_utils.read_image(right_p)
    disp = frame_utils.read_gen(disp_p)
    flow = np.stack([-disp, np.zeros_like(disp)], axis=-1)
    decode_ms = t(lambda: (frame_utils.read_image(left_p),
                           frame_utils.read_image(right_p),
                           frame_utils.read_gen(disp_p)))
    color_ms = t(lambda: aug._color(img1, img2, rngf()))
    c1, c2 = aug._color(img1, img2, rngf())
    e2 = _eraser(c2, rngf())
    spatial_ms = t(lambda: aug._spatial(c1, e2, flow, rngf()))
    return {"decode_ms": round(decode_ms, 1),
            "photometric_ms": round(color_ms, 1),
            "spatial_ms": round(spatial_ms, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pairs", type=int, default=64)
    ap.add_argument("--batches", type=int, default=12)
    ap.add_argument("--workers", type=int, nargs="*", default=[0, 2, 4, 8])
    ap.add_argument("--device", action="store_true",
                    help="combined run: real loader -> jitted train step on "
                         "the accelerator (compiles the full step)")
    ap.add_argument("--root", default=None,
                    help="reuse an existing tree instead of building one")
    args = ap.parse_args()

    from raft_stereo_tpu import native
    from raft_stereo_tpu.telemetry.events import bench_record

    root = args.root or tempfile.mkdtemp(prefix="loaderbench_")
    if not args.root:
        build_tree(root, args.pairs)

    # Shared versioned run header (telemetry/events.py); the per-config
    # lines below are rows under it.
    print(json.dumps(bench_record(
        {"metric": "loader_bench_run", "pairs": args.pairs,
         "batches": args.batches, "workers": args.workers,
         "device": args.device})))
    print(json.dumps({"metric": "loader_stage_breakdown_ms",
                      **stage_breakdown(root), "unit": "ms/sample"}))

    for w in args.workers:
        for wt in (("thread",) if w == 0 else ("thread", "process")):
            for photometric in (True, False):
                ips = measure_host(root, w, args.batches,
                                   photometric=photometric, worker_type=wt)
                print(json.dumps({
                    "metric": "loader_images_per_s", "workers": w,
                    "worker_type": wt, "host_photometric": photometric,
                    "native_decoders": native.available(),
                    "value": round(ips, 2),
                    "unit": f"images/s (540x960 -> {CROP})"}))

    if args.device:
        import functools

        import jax
        import jax.numpy as jnp

        from raft_stereo_tpu.config import RaftStereoConfig, TrainConfig
        from raft_stereo_tpu.training.state import create_train_state
        from raft_stereo_tpu.training.step import train_step

        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 10)

        from raft_stereo_tpu.data.device_jitter import params_for_datasets

        model_cfg = RaftStereoConfig(mixed_precision=True)
        train_cfg = TrainConfig(batch_size=BATCH, train_iters=22,
                                image_size=CROP)
        state = create_train_state(model_cfg, train_cfg,
                                   jax.random.PRNGKey(0),
                                   image_shape=(1,) + CROP + (3,))
        step = jax.jit(functools.partial(
            train_step, iters=22, loss_gamma=train_cfg.loss_gamma,
            max_flow=train_cfg.max_flow), donate_argnums=(0,))
        step_devjit = jax.jit(functools.partial(
            train_step, iters=22, loss_gamma=train_cfg.loss_gamma,
            max_flow=train_cfg.max_flow,
            jitter=params_for_datasets(("sceneflow",))), donate_argnums=(0,))

        from raft_stereo_tpu.training.train_loop import _DevicePrefetcher

        def run(batch_iter, n, prefetch: bool, step_fn=None):
            """``prefetch`` runs the host->device upload on the train
            loop's own _DevicePrefetcher thread (the product path);
            without it the upload is serial with dispatch."""
            nonlocal state
            step_fn = step_fn or step
            metrics = None
            it = (_DevicePrefetcher(batch_iter, jax.device_put)
                  if prefetch else
                  ({k: jnp.asarray(v) for k, v in b.items()}
                   for b in batch_iter))
            t0 = time.perf_counter()
            for _ in range(n):
                state, metrics = step_fn(state, next(it))
            # device_get is a REAL transfer (block_until_ready returns at
            # dispatch behind this env's async tunnel — bench.py), so the
            # stop clock includes every dispatched step.
            jax.device_get(metrics["loss"])
            dt = (time.perf_counter() - t0) / n
            if prefetch:
                it.close()
            return dt

        loader = make_loader(root, workers=max(args.workers))
        real_it = iter(loader)
        first = next(real_it)  # compile against a real batch

        def synth_iter():
            while True:
                yield dict(first)

        run(synth_iter(), 1, prefetch=False)  # compile + warm
        synth_s = run(synth_iter(), args.batches, prefetch=False)
        synth_pf_s = run(synth_iter(), args.batches, prefetch=True)
        real_s = run(real_it, args.batches, prefetch=True)
        print(json.dumps({
            "metric": "combined_loader_train_step",
            "value": round(real_s, 4),
            "unit": "s/step (real loader + device prefetch)",
            "synthetic_batch_s": round(synth_s, 4),
            "synthetic_batch_prefetch_s": round(synth_pf_s, 4),
            "host_overhead_pct": round(100 * (real_s / synth_pf_s - 1), 1)}))

        # Same combined run with photometric moved on-device: host loader
        # skips ColorJitter (78% of its per-sample CPU), the train step
        # applies the jitter inside the compiled program.
        dj_loader = make_loader(root, workers=max(args.workers),
                                photometric=False)
        dj_it = iter(dj_loader)
        first_dj = next(dj_it)
        run(iter([first_dj]), 1, prefetch=False,
            step_fn=step_devjit)  # compile the devjit variant
        devjit_s = run(dj_it, args.batches, prefetch=True,
                       step_fn=step_devjit)
        print(json.dumps({
            "metric": "combined_loader_train_step_device_photometric",
            "value": round(devjit_s, 4),
            "unit": "s/step (real loader, jitter on device)",
            "vs_host_jitter": round(devjit_s / real_s, 3),
            "synthetic_batch_prefetch_s": round(synth_pf_s, 4),
            "host_overhead_pct":
                round(100 * (devjit_s / synth_pf_s - 1), 1)}))


if __name__ == "__main__":
    main()
