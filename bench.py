"""Benchmark: realtime-config RAFT-Stereo inference FPS at KITTI resolution.

Replicates the reference's FPS protocol (reference: evaluate_stereo.py:77-82,
105-107): test-mode forward, inputs padded to /32 (375x1242 -> 384x1248),
warmup discarded, FPS = 1 / mean(per-image runtime).  Model is the realtime
configuration (reference: README.md:84 — shared backbone, n_downsample 3,
2 GRU layers, slow-fast, 7 iters, mixed precision).

Timing method: the device may sit behind an async tunnel where
``block_until_ready`` returns at dispatch, so per-call host timing lies.
Instead we chain K forwards on-device in a ``lax.fori_loop`` (inputs perturbed
per-iteration so nothing folds away), fetch a scalar, and difference two K
values to cancel dispatch/round-trip overhead:
    per_image = (t(K_hi) - t(K_lo)) / (K_hi - K_lo)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` divides by 26 FPS — the reference paper's realtime-model
RTX-6000 claim (arXiv 2109.07547; external, see BASELINE.md — the repo
publishes no measured number, so the denominator inherits the paper's
uncertainty).  Chip-side variance behind this environment's tunnel is
±20%+ run to run (throttling / shared tenancy — BENCH_TRAIN_r02.json's
roofline probes quantify it); compare trends, not single runs.  North star
(BASELINE.json): vs_baseline >= 4.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_FPS = 26.0  # reference realtime model on RTX 6000 (paper claim)
KITTI_PADDED = (384, 1248)  # 375x1242 padded to /32 (evaluate_stereo.py:73)
K_LO, K_HI = 3, 23
REPEATS = 3


def main():
    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    cfg = RaftStereoConfig.realtime()
    model = RAFTStereo(cfg)

    h, w = KITTI_PADDED
    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)

    variables = jax.jit(
        lambda r: model.init(r, img1[:, :64, :96], img2[:, :64, :96],
                             iters=1, test_mode=True)
    )(jax.random.PRNGKey(0))

    from raft_stereo_tpu.profiling import (chained_seconds_per_call,
                                           make_forward_chain)

    # scalar float() fetch inside the chain = full sync even behind the
    # async tunnel (see profiling.make_forward_chain)
    make_chain = make_forward_chain(
        lambda v, a, b: model.apply(v, a, b, iters=7, test_mode=True)[1],
        variables, img1, img2)
    per_image = chained_seconds_per_call(make_chain, k_lo=K_LO, k_hi=K_HI,
                                         repeats=REPEATS)
    fps = 1.0 / per_image
    print(json.dumps({
        "metric": "realtime_model_inference_fps_kitti_res",
        "value": round(fps, 2),
        "unit": "frames/s",
        "vs_baseline": round(fps / BASELINE_FPS, 3),
    }))


if __name__ == "__main__":
    main()
