"""Benchmark: realtime-config RAFT-Stereo inference FPS at KITTI resolution.

Replicates the reference's FPS protocol (reference: evaluate_stereo.py:77-82,
105-107): test-mode forward, inputs padded to /32 (375x1242 -> 384x1248),
warmup discarded, FPS = 1 / mean(per-image runtime).  Model is the realtime
configuration (reference: README.md:84 — shared backbone, n_downsample 3,
2 GRU layers, slow-fast, 7 iters, mixed precision).

Timing method: the device may sit behind an async tunnel where
``block_until_ready`` returns at dispatch, so per-call host timing lies.
Instead we chain K forwards on-device in a ``lax.fori_loop`` (inputs perturbed
per-iteration so nothing folds away), fetch a scalar, and difference two K
values to cancel dispatch/round-trip overhead:
    per_image = (t(K_hi) - t(K_lo)) / (K_hi - K_lo)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` divides by 26 FPS — the reference paper's realtime-model
RTX-6000 claim (arXiv 2109.07547; external, see BASELINE.md — the repo
publishes no measured number, so the denominator inherits the paper's
uncertainty).  Chip-side variance behind this environment's tunnel is
±20%+ run to run (throttling / shared tenancy — BENCH_TRAIN_r02.json's
roofline probes quantify it); compare trends, not single runs.  North star
(BASELINE.json): vs_baseline >= 4.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_FPS = 26.0  # reference realtime model on RTX 6000 (paper claim)
KITTI_PADDED = (384, 1248)  # 375x1242 padded to /32 (evaluate_stereo.py:73)
BENCH_ITERS = 7             # realtime model --valid_iters
K_LO, K_HI = 3, 23
REPEATS = 3
BASELINE_JSON = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE.json")
# Warn only past clear noise: chip-side variance behind this environment's
# tunnel is ±20%+ run to run (module docstring), so a regression line below
# that would fire on healthy runs.
REGRESSION_FACTOR = 1.25


def _seconds_per_forward(model, variables, img1, img2, iters):
    from raft_stereo_tpu.profiling import (chained_seconds_per_call,
                                           make_forward_chain)

    # scalar float() fetch inside the chain = full sync even behind the
    # async tunnel (see profiling.make_forward_chain)
    make_chain = make_forward_chain(
        lambda v, a, b: model.apply(v, a, b, iters=iters, test_mode=True)[1],
        variables, img1, img2)
    return chained_seconds_per_call(make_chain, k_lo=K_LO, k_hi=K_HI,
                                    repeats=REPEATS)


def phase_split(t_iters_s: float, t_one_iter_s: float, iters: int) -> dict:
    """First-class encoder-vs-GRU attribution (the ad-hoc round-3
    measurement, INFERENCE_PROFILE_r03.json): differencing the chained
    ``iters``-iteration and 1-iteration forwards isolates the per-GRU-iter
    cost; everything else (encoders, corr pyramid, final upsample,
    dispatch) is the fixed remainder."""
    per_iter = (t_iters_s - t_one_iter_s) / (iters - 1)
    fixed = t_one_iter_s - per_iter
    return {
        "metric": "realtime_phase_split",
        f"t_iters{iters}_ms": round(t_iters_s * 1e3, 3),
        "t_iters1_ms": round(t_one_iter_s * 1e3, 3),
        "per_gru_iter_ms": round(per_iter * 1e3, 4),
        "encoder_and_fixed_ms": round(fixed * 1e3, 4),
        f"gru_share_at_{iters}_iters": round(
            per_iter * iters / t_iters_s, 3),
    }


def check_regression(split: dict, fps: float) -> list:
    """Compare this run against BASELINE.json's published numbers; return
    warn lines (printed as JSON) when a phase regressed past the noise
    band.  Attribution first: the per-GRU-iter number is the one the fused
    update-block kernel moves."""
    warnings = []
    try:
        with open(BASELINE_JSON) as f:
            published = json.load(f).get("published", {})
    except (OSError, ValueError):
        return warnings
    ref = published.get("realtime_phase_split")
    if ref:
        for key in ("per_gru_iter_ms", "encoder_and_fixed_ms"):
            if key in ref and split[key] > REGRESSION_FACTOR * ref[key]:
                warnings.append({
                    "warning": f"{key} regressed vs BASELINE.json",
                    "value_ms": split[key],
                    "baseline_ms": ref[key],
                    "baseline_source": ref.get("source", "BASELINE.json"),
                })
    north_star = published.get("north_star_vs_baseline")
    if north_star and fps / BASELINE_FPS < north_star / REGRESSION_FACTOR:
        warnings.append({
            "warning": "fps fell below the north-star band",
            "vs_baseline": round(fps / BASELINE_FPS, 3),
            "north_star": north_star,
        })
    return warnings


def tier_latency_split(cfg, variables, img1, img2, fixed_s: float) -> list:
    """Per-tier chained latency at the bench's fixed input vs the
    fixed-depth program (config.REQUEST_TIERS — the serving engine's
    per-request early-exit presets).  Random bench inputs on seeded init
    weights rarely converge, so ``iters_used`` is reported next to every
    time: the latency win is a function of the OBSERVED trip count
    (EARLY_EXIT_r12.json carries the trained-weights curve); a tier may
    tie the baseline here but must never exceed it beyond the noise band
    (warn line)."""
    from raft_stereo_tpu.config import REQUEST_TIERS
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo

    rows = []
    for tier in REQUEST_TIERS.values():
        t_cfg = tier.apply(cfg)
        t_model = RAFTStereo(t_cfg)
        adaptive = t_cfg.exit_threshold_px > 0
        t_vars = variables
        if t_cfg.quant != "off":
            # The chained bench applies the model directly (not through
            # make_forward's int8-tree program), so feed the int8
            # ROUND-TRIPPED weights: the math matches the serving turbo
            # tier exactly; the HBM-residency half of the win is what
            # bench_serve.py's tier sweep measures through the engine.
            from raft_stereo_tpu.quant import (dequantize_variables,
                                               quantize_variables)
            t_vars = dequantize_variables(quantize_variables(variables))
        secs = _seconds_per_forward(t_model, t_vars, img1, img2,
                                    BENCH_ITERS)
        if adaptive:   # one un-chained apply fetches the trip count
            out = t_model.apply(t_vars, img1, img2, iters=BENCH_ITERS,
                                test_mode=True)
            iters_used = int(out[2])
        else:
            iters_used = BENCH_ITERS
        row = {
            "tier": tier.name,
            "exit_threshold_px": tier.exit_threshold_px,
            "min_iters": tier.min_iters,
            "quant": tier.quant,
            "per_image_ms": round(secs * 1e3, 3),
            "vs_fixed": round(secs / fixed_s, 3),
            "iters_used": iters_used,
            "iters_cap": BENCH_ITERS,
        }
        if secs > REGRESSION_FACTOR * fixed_s:
            row["warning"] = (f"tier {tier.name} is {secs / fixed_s:.2f}x "
                              f"the fixed-depth program — early-exit "
                              f"overhead regression")
        rows.append(row)
    return rows


def main():
    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.telemetry.costs import aot_cost_summary
    from raft_stereo_tpu.telemetry.events import bench_record

    cfg = RaftStereoConfig.realtime()
    model = RAFTStereo(cfg)

    h, w = KITTI_PADDED
    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)

    variables = jax.jit(
        lambda r: model.init(r, img1[:, :64, :96], img2[:, :64, :96],
                             iters=1, test_mode=True)
    )(jax.random.PRNGKey(0))

    per_image = _seconds_per_forward(model, variables, img1, img2,
                                     BENCH_ITERS)
    t_one = _seconds_per_forward(model, variables, img1, img2, 1)
    fps = 1.0 / per_image
    # Cost denominator (telemetry/costs.py): the bench forward's compiled
    # flops/bytes ride the record, so every BENCH_*.json carries the
    # model-required work next to the measured time — measured seconds x
    # this flops number over the device peak IS the bench's MFU.
    cost = aot_cost_summary(
        jax.jit(lambda v, a, b: model.apply(v, a, b, iters=BENCH_ITERS,
                                            test_mode=True)[1]),
        variables, img1, img2)
    # Shared versioned header (telemetry/events.py): schema_version + the
    # run's device topology/timestamp ride the primary record.
    print(json.dumps(bench_record({
        "metric": "realtime_model_inference_fps_kitti_res",
        "value": round(fps, 2),
        "unit": "frames/s",
        "vs_baseline": round(fps / BASELINE_FPS, 3),
    }, cost=cost)))
    split = phase_split(per_image, t_one, BENCH_ITERS)
    split["fused_gru"] = cfg.fused_gru
    print(json.dumps(split))
    # Per-tier chained latency (adaptive early exit, config.REQUEST_TIERS)
    # against the fixed-depth program just measured.
    print(json.dumps({
        "metric": "realtime_tier_latency",
        "fixed_per_image_ms": round(per_image * 1e3, 3),
        "tiers": tier_latency_split(cfg, variables, img1, img2, per_image),
    }))
    for warning in check_regression(split, fps):
        print(json.dumps(warning))


if __name__ == "__main__":
    main()
