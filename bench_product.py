"""Product-path FPS: the REAL KITTI evaluation harness on the chip.

bench.py times a bare on-device forward chain; the reference's protocol
(reference: evaluate_stereo.py:60-109) runs a Python loop with a per-image
host->device copy, /32 pad, forward, unpad, and device->host fetch.  This
script runs OUR product harness — ``eval.validate.validate_kitti`` over a
synthetic KITTI-layout tree at the real 375x1242 resolution (the honest
per-image stop clock is the result fetch; see eval/runner.py) — next to the
bare-forward chained measurement, so the flagship FPS number and the
product path finally meet and their gap is a measurement.

Prints one JSON line (bench.py contract): value = product-path FPS;
``bare_forward_fps`` and ``gap`` fields explain the difference (per-image
Python/dispatch/copy overhead on this host).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_REPO, "tests"))

N_IMAGES = 70          # warmup discards the first 50 (evaluate_stereo.py:105)
KITTI_HW = (375, 1242)
ITERS = 7              # realtime protocol depth (bench.py)
K_LO, K_HI = 3, 23
REPEATS = 3


def main():
    from golden_data import make_kitti

    from raft_stereo_tpu.config import RaftStereoConfig
    from raft_stereo_tpu.eval.runner import InferenceRunner
    from raft_stereo_tpu.eval.validate import validate_kitti
    from raft_stereo_tpu.models.raft_stereo import RAFTStereo
    from raft_stereo_tpu.profiling import (chained_seconds_per_call,
                                           make_forward_chain)

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 10)

    cfg = RaftStereoConfig.realtime()
    model = RAFTStereo(cfg)
    img_s = jnp.zeros((1, 64, 96, 3), jnp.float32)
    variables = jax.jit(lambda r: model.init(r, img_s, img_s, iters=1,
                                             test_mode=True)
                        )(jax.random.PRNGKey(0))

    # --- product path: the real KITTI validator over a synthetic tree
    with tempfile.TemporaryDirectory(prefix="kittibench_") as td:
        root = os.path.join(td, "KITTI")
        make_kitti(root, np.random.default_rng(0), n=N_IMAGES, hw=KITTI_HW,
                   hard=True)
        runner = InferenceRunner(cfg, variables, iters=ITERS)
        res = validate_kitti(runner, root=root)

        # --- batched product mode: upload BATCH pairs per round trip.
        # Amortizes the tunnel RTT + per-image transfer setup the per-image
        # protocol pays 1x per frame (PRODUCT_r03.json decomposition); any
        # real remote deployment would batch the same way.
        from raft_stereo_tpu.data.frame_utils import read_image
        BATCHED_N = 8
        lefts = [read_image(os.path.join(root, "training", "image_2",
                                         f"{i:06d}_10.png"))
                 for i in range(BATCHED_N)]
        rights = [read_image(os.path.join(root, "training", "image_3",
                                          f"{i:06d}_10.png"))
                  for i in range(BATCHED_N)]
        runner.run_batch(lefts, rights)  # compile + warm
        batched = [runner.run_batch(lefts, rights)[1] for _ in range(5)]
        batched_s = float(np.median(batched)) / BATCHED_N
        flows_fp32, _ = runner.run_batch(lefts, rights)

        # --- half-precision fetch (round 5): the flow is cast fp16 ON
        # DEVICE before the fetch, halving the down-leg bytes that dominate
        # the batched path (PRODUCT_r04: batched mode reached only 59% of
        # the fp32-fetch ceiling; the fetch leg was 162.7 ms/image).
        runner16 = InferenceRunner(cfg, variables, iters=ITERS,
                                   fetch_dtype="fp16")
        runner16.run_batch(lefts, rights)  # compile + warm
        batched16 = [runner16.run_batch(lefts, rights)[1] for _ in range(5)]
        batched16_s = float(np.median(batched16)) / BATCHED_N
        flows_fp16, _ = runner16.run_batch(lefts, rights)
        # pure fetch-rounding error — bounds any EPE delta from above
        fetch_roundoff_px = float(np.abs(flows_fp16 - flows_fp32).mean())

    # --- bare forward at the same padded shape (bench.py's method)
    h = -(-KITTI_HW[0] // 32) * 32
    w = -(-KITTI_HW[1] // 32) * 32
    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)

    bare_s = chained_seconds_per_call(
        make_forward_chain(
            lambda v, a, b: model.apply(v, a, b, iters=ITERS,
                                        test_mode=True)[1],
            variables, img1, img2),
        k_lo=K_LO, k_hi=K_HI, repeats=REPEATS)

    # --- decompose the per-image overhead: device round-trip latency and
    # host<->device transfer, measured in the same run (behind a remote
    # tunnel these — not dispatch count — dominate; an interleaved A/B of
    # the fused vs eager-pad runner measured 701 vs 676 ms/image, equal
    # within noise, while the same path varies 410-690 ms across hours).
    import time as _time

    def med(f, n=7):
        ts = []
        for i in range(n):
            t0 = _time.perf_counter()
            f(i)
            ts.append(_time.perf_counter() - t0)
        return float(np.median(ts)) * 1e3

    rtt_ms = med(lambda i: float(jnp.sum(jnp.asarray(np.float32(i)))))
    pair = np.zeros((2,) + KITTI_HW + (3,), np.uint8)
    up_ms = med(lambda i: float(jnp.sum(
        jnp.asarray(pair) * np.float32(1 + i)))) - rtt_ms
    big = jnp.zeros(KITTI_HW, jnp.float32) + 1.0
    jax.device_get(big)
    down_ms = med(lambda i: np.asarray(big + np.float32(i))) - rtt_ms
    big16 = jnp.zeros(KITTI_HW, jnp.float16) + jnp.float16(1.0)
    jax.device_get(big16)
    down16_ms = med(lambda i: np.asarray(big16 + np.float16(i))) - rtt_ms

    fps_product = res["kitti-fps"]
    fps_bare = 1.0 / bare_s
    # Bandwidth ceiling of ANY product mode behind this tunnel: each image
    # must move 2 uint8 views up and 1 f32 flow down regardless of
    # batching; at the same-run measured transfer rates that floor alone
    # caps FPS.  Batching amortizes only the RTT share — when the tunnel
    # is bandwidth-bound (it is here: ~30 MB/s up, ~11 MB/s down) batched
    # mode approaches this ceiling, not the 148 img/s on-device rate.
    # Clamp: on a LOCAL (non-tunneled) device the median-minus-RTT probes
    # can come out ~0 or negative — report no ceiling instead of nonsense.
    transfer_floor_s = (up_ms + down_ms) / 1e3
    transfer_floor16_s = (up_ms + down16_ms) / 1e3
    from raft_stereo_tpu.telemetry.events import bench_record, write_record

    has_floor = transfer_floor_s > 1e-4
    has_floor16 = transfer_floor16_s > 1e-4
    rec = bench_record({
        "metric": "product_path_fps_kitti",
        "value": round(fps_product, 2),
        "unit": "frames/s (validate_kitti end-to-end, 375x1242)",
        "batched_fps": round(1.0 / batched_s, 2),
        "batched_n_per_roundtrip": BATCHED_N,
        "tunnel_bandwidth_ceiling_fps": (
            round(1.0 / transfer_floor_s, 2) if has_floor else None),
        "batched_vs_bandwidth_ceiling": (
            round(transfer_floor_s / batched_s, 3) if has_floor else None),
        "batched_fp16_fetch_fps": round(1.0 / batched16_s, 2),
        "fp16_fetch_ceiling_fps": (
            round(1.0 / transfer_floor16_s, 2) if has_floor16 else None),
        "batched_fp16_vs_its_ceiling": (
            round(transfer_floor16_s / batched16_s, 3) if has_floor16
            else None),
        "fp16_fetch_roundoff_px": round(fetch_roundoff_px, 5),
        "tunnel_fetch_flow_fp16_ms": round(down16_ms, 1),
        "bare_forward_fps": round(fps_bare, 2),
        "gap": round(fps_product / fps_bare, 3),
        "per_image_overhead_ms": round(1e3 * (1 / fps_product - bare_s), 2),
        "tunnel_rtt_ms": round(rtt_ms, 1),
        "tunnel_upload_pair_ms": round(up_ms, 1),
        "tunnel_fetch_flow_ms": round(down_ms, 1),
        "kitti_epe_random_weights": round(res["kitti-epe"], 2),
        "n_timed": N_IMAGES - 50,  # FpsProtocol times images 51..N
    })
    print(json.dumps(rec))
    write_record(os.path.join(_REPO, "PRODUCT_r05.json"), rec)


if __name__ == "__main__":
    main()
